"""Serve request-path tracing, per-tenant metrics, SLO accounting and the
open-loop load generator (ISSUE-14):

- off-mode inertness: ``tpu_serve_request_log=off`` (default) lowers the
  SAME predict HLO as ``on``, and ARMED tracing still costs exactly one
  compiled dispatch + one host sync per raw predict (zero device work);
- phase breakdown: queue-wait / assemble / dispatch / post sums match the
  recorded total latency;
- deterministic sampling (fixed request stream -> same sampled event set
  every run) and the bounded top-K slow-request exemplar ring;
- labeled Prometheus exposition: two named tenants render DISTINCT
  ``{model="..."}`` series with a schema stable across scrapes;
- registry ``Histogram`` log-bucket percentiles vs numpy on synthetic
  data (full-run quantiles, not a reservoir window);
- ``tools/serve_load.py``: byte-identical seeded arrival schedules, and
  a deliberately-overloaded open-loop run whose p99 is dominated by
  queue wait — the signal closed-loop timing structurally cannot see.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import serve, telemetry
from lightgbm_tpu.serve.metrics import SLOW_RING_SIZE, ServeMetrics

pytestmark = pytest.mark.serve

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _data(n=1200, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    return X, y


def _booster(extra=None, n=1200, seed=0, iters=3):
    X, y = _data(n, seed=seed)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "metric": "none"}
    params.update(extra or {})
    return X, lgb.train(params, lgb.Dataset(X, label=y), iters)


TRACE_ON = {"tpu_serve_request_log": "on",
            "tpu_serve_request_sample": 1.0,
            "tpu_serve_slow_ms": 1e-7}


# ----------------------------------------------------------- knob validation
def test_request_log_knob_validated():
    X, bst = _booster({"tpu_serve_request_log": "sometimes"})
    with pytest.raises(ValueError, match="tpu_serve_request_log"):
        serve.Predictor(bst)


# -------------------------------------------------------- off-mode inertness
def test_off_mode_lowered_hlo_identical():
    """The tracing knob never enters a traced program: the plan's jitted
    predict program lowers to IDENTICAL HLO text with tracing off
    (default) vs armed — the PR-9 inertness contract extended to the
    tpu_serve_* knobs."""
    texts = []
    for extra in ({}, TRACE_ON):
        X, bst = _booster(extra)
        serve.clear_plan_cache()
        pred = serve.Predictor(bst, raw_score=True)
        assert pred.metrics.tracer.armed == bool(extra)
        plan = pred.plan
        bins = np.zeros((32, plan.num_features), np.int32)
        import jax.numpy as jnp
        texts.append(plan._jit_binned.lower(
            plan._arrays, jnp.asarray(bins)).as_text())
    serve.clear_plan_cache()
    assert texts[0] == texts[1]


def test_armed_census_one_dispatch_one_sync():
    """ARMED tracing adds ZERO device dispatches: a raw predict stays
    exactly 1 compiled dispatch + 1 host sync per call with the request
    log on (phase marks are host perf_counter reads at dispatch
    boundaries)."""
    import jax

    X, bst = _booster(TRACE_ON)
    pred = serve.Predictor(bst, raw_score=True)
    assert pred.metrics.tracer.armed
    plan = pred.plan
    pred.predict(X[:64])                     # compile outside the census
    counts = {"dispatch": 0, "sync": 0}
    orig_call = plan._call
    orig_get = jax.device_get

    def counting_call(*a, **k):
        counts["dispatch"] += 1
        return orig_call(*a, **k)

    def counting_get(x):
        counts["sync"] += 1
        return orig_get(x)

    plan._call = counting_call
    jax.device_get = counting_get
    try:
        for _ in range(4):
            pred.predict(X[:64])
    finally:
        jax.device_get = orig_get
        plan._call = orig_call
    assert counts["dispatch"] == 4, counts
    assert counts["sync"] == 4, counts
    # ... and the tracer actually recorded those requests
    assert pred.metrics.tracer._n >= 4


# ----------------------------------------------------------- phase breakdown
def test_phase_sum_matches_total_direct():
    X, bst = _booster(TRACE_ON)
    pred = serve.Predictor(bst)
    for _ in range(6):
        pred.predict(X[:32])
    snap = pred.metrics_snapshot()
    assert snap["phases"] is not None
    for phase in ("queue_wait", "assemble", "dispatch", "post", "total"):
        assert snap["phases"][phase]["count"] == 6
    ring = snap["slow_requests"]             # slow_ms ~ 0: every request
    assert ring, "exemplar ring empty with slow_ms ~ 0"
    for entry in ring:
        phase_sum = (entry["queue_wait_ms"] + entry["assemble_ms"]
                     + entry["dispatch_ms"] + entry["post_ms"])
        # marks are contiguous perf_counter deltas inside predict(): the
        # sum reproduces the recorded total up to the record-path tail
        assert abs(phase_sum - entry["total_ms"]) \
            <= max(0.05 * entry["total_ms"], 0.5), entry
        assert entry["queue_wait_ms"] == 0.0     # direct path: no queue


def test_batcher_queue_wait_and_coalescing_context():
    X, bst = _booster(TRACE_ON)
    pred = serve.Predictor(bst)
    pred.predict(X[:64])                     # absorb compiles
    mb = pred.batcher(max_batch=256, max_wait_ms=30)
    futs = [mb.submit(X[i:i + 2]) for i in range(0, 16, 2)]
    for f in futs:
        f.result(timeout=60)
    mb.close()
    ring = pred.metrics.tracer.slow_requests()
    batched = [e for e in ring if e["coalesced"] > 1]
    assert batched, ring
    for entry in batched:
        assert entry["batch_rows"] >= entry["rows"]
        assert entry["queue_wait_ms"] >= 0.0
        phase_sum = (entry["queue_wait_ms"] + entry["assemble_ms"]
                     + entry["dispatch_ms"] + entry["post_ms"])
        assert abs(phase_sum - entry["total_ms"]) \
            <= max(0.10 * entry["total_ms"], 1.0), entry


# ------------------------------------------------------------------ sampling
def _sampled_ids(tmp_path, tag):
    """Run a fixed 16-request stream at sample=0.25 (slow override off)
    and return the req_ids that emitted serve.request events."""
    log = str(tmp_path / f"req_{tag}.jsonl")
    X, bst = _booster({"tpu_serve_request_log": "on",
                       "tpu_serve_request_sample": 0.25,
                       "tpu_serve_slow_ms": 0.0})
    serve.clear_plan_cache()
    pred = serve.Predictor(bst)
    telemetry.configure_log(log)
    try:
        for _ in range(16):
            pred.predict(X[:32])
    finally:
        telemetry.close_log()
    ids = []
    with open(log) as fh:
        for line in fh:
            e = json.loads(line)
            if e.get("kind") == "serve.request":
                ids.append(e["req_id"])
                assert e["slow"] is False
                assert e["total_s"] > 0
    return ids


def test_sampling_deterministic(tmp_path):
    """rate=0.25 samples EXACTLY every 4th request of the sequence —
    deterministic pacing, so two identical streams emit the same event
    set (no RNG in the sampling decision)."""
    first = _sampled_ids(tmp_path, "a")
    second = _sampled_ids(tmp_path, "b")
    assert first == [3, 7, 11, 15]
    assert second == first


def test_slow_requests_always_sampled_and_ring_bounded(tmp_path):
    log = str(tmp_path / "slow.jsonl")
    X, bst = _booster({"tpu_serve_request_log": "on",
                       "tpu_serve_request_sample": 0.0,   # rate: never
                       "tpu_serve_slow_ms": 1e-7})        # slow: always
    serve.clear_plan_cache()
    pred = serve.Predictor(bst)
    telemetry.configure_log(log)
    try:
        n_req = SLOW_RING_SIZE + 8
        for _ in range(n_req):
            pred.predict(X[:32])
    finally:
        telemetry.close_log()
    with open(log) as fh:
        slow_events = [json.loads(line) for line in fh
                       if '"serve.request"' in line]
    assert len(slow_events) == n_req        # slow bypasses the 0.0 rate
    assert all(e["slow"] for e in slow_events)
    ring = pred.metrics.tracer.slow_requests()
    assert len(ring) == SLOW_RING_SIZE      # bounded top-K
    totals = [e["total_ms"] for e in ring]
    assert totals == sorted(totals, reverse=True)


# ------------------------------------------------- per-tenant labeled metrics
def test_two_tenant_labeled_prometheus_stable():
    """Two named tenants in one process render DISTINCT labeled series
    (the multi-Booster aliasing fix), the registry carries both labeled
    counter sets, per-tenant plan-cache bytes attribute correctly, and
    the exposition schema is stable across scrapes."""
    Xa, bst_a = _booster(seed=1)
    Xb, bst_b = _booster(seed=2)
    serve.clear_plan_cache()
    pa = serve.Predictor(bst_a, name="tenant_a")
    pb = serve.Predictor(bst_b, name="tenant_b")
    for _ in range(3):
        pa.predict(Xa[:32])
    pb.predict(Xb[:32])

    text_a = pa.metrics.render_prometheus(plan=pa.plan)
    text_b = pb.metrics.render_prometheus(plan=pb.plan)
    assert 'lgbm_tpu_serve_requests{model="tenant_a"} 3.0' in text_a
    assert 'lgbm_tpu_serve_requests{model="tenant_b"} 1.0' in text_b
    # per-PREDICTOR series never leak the other tenant (the process-
    # global plan_cache block legitimately shows every tenant's bytes)
    assert 'lgbm_tpu_serve_requests{model="tenant_b"}' not in text_a
    assert 'lgbm_tpu_serve_rows{model="tenant_b"}' not in text_a
    # ... and the process-global cache block attributes BOTH tenants
    assert 'lgbm_tpu_serve_plan_cache_bytes{model="tenant_b"}' in text_a
    # one scrape of the process registry sees BOTH tenants' series
    reg_text = telemetry.render_prometheus(telemetry.registry().snapshot(),
                                           prefix="lgbm_tpu")
    assert 'lgbm_tpu_counters_serve_requests{model="tenant_a"}' in reg_text
    assert 'lgbm_tpu_counters_serve_requests{model="tenant_b"}' in reg_text
    # per-tenant plan-cache byte attribution (ROADMAP-1 admission input)
    stats = serve.cache_stats()
    key_a, key_b = 'bytes{model="tenant_a"}', 'bytes{model="tenant_b"}'
    assert stats[key_a] == pa.plan.plan_bytes
    assert stats[key_b] == pb.plan.plan_bytes
    assert stats[key_a] + stats[key_b] <= stats["bytes"]
    reg = telemetry.registry()
    assert reg.gauge("serve.plan_cache_bytes",
                     labels={"model": "tenant_a"}).value \
        == pa.plan.plan_bytes
    # schema stability: a second scrape renders the same series set
    def series(text):
        return sorted(line.split(" ")[0] for line in text.splitlines()
                      if not line.startswith("#"))
    assert series(text_a) == series(pa.metrics.render_prometheus(
        plan=pa.plan))
    serve.clear_plan_cache()
    # evicted tenants' byte gauges drop to 0 instead of lingering
    assert reg.gauge("serve.plan_cache_bytes",
                     labels={"model": "tenant_a"}).value == 0


# ------------------------------------------------------- bucket percentiles
def test_bucket_percentiles_vs_numpy():
    """Full-run log-bucket quantiles track numpy within the documented
    bucket resolution (one 10^(1/24) ~ 1.10 ratio step) on synthetic
    lognormal latencies — and cover ALL observations, unlike the old
    4096-deque window."""
    from lightgbm_tpu.telemetry.registry import Histogram
    rng = np.random.RandomState(3)
    vals = np.exp(rng.randn(30000) * 0.8 - 6.0)     # ~ms-scale latencies
    h = Histogram("t", threading.Lock(), reservoir=128)
    for v in vals:
        h.observe(v)
    for q, pct in ((0.5, 50), (0.99, 99), (0.999, 99.9)):
        est = h.quantiles((q,))[0]
        ref = float(np.percentile(vals, pct))
        assert abs(est / ref - 1) < 0.12, (q, est, ref)
    # the reservoir holds only 128 values — the buckets still aggregate
    # the full 30k history (the window bug this replaces)
    assert h.count == 30000
    assert h.reservoir_values().size == 128
    s = h.summary()
    assert s["p999"] >= s["p99"] >= s["p50"]
    assert s["max"] == float(vals.max())


def test_serve_metrics_full_run_percentiles():
    """ServeMetrics quantiles cover observations past the reservoir
    window: 5000 fast requests then 100 slow ones — a trailing-4096
    window would force p50 toward the recent mix; the full-run buckets
    keep p50 at the fast mode."""
    m = ServeMetrics(reservoir=64)
    for _ in range(5000):
        m.observe_request(1, 0.001)
    for _ in range(100):
        m.observe_request(1, 0.5)
    q = m.latency_quantiles_ms()
    assert q["p50_ms"] < 2.0, q            # fast mode, full history
    assert q["p99_ms"] > 100.0, q          # tail sees the slow burst
    assert q["p999_ms"] >= q["p99_ms"]


# ------------------------------------------------------------ SLO accounting
def test_slo_accounting_attainment_burn_and_attribution():
    m = ServeMetrics(model="slo_tenant", slo_p99_ms=10.0)
    for _ in range(18):
        m.observe_request(1, 0.001)        # 1ms: meets the 10ms target
    m.observe_request(1, 0.5)              # 500ms: latency violation
    m.observe_shed()                       # shed: violation, attributed
    snap = m.snapshot()
    slo = snap["slo"]
    assert slo["target_p99_ms"] == 10.0
    assert slo["window_requests"] == 20
    assert slo["attainment"] == pytest.approx(18 / 20)
    # 10% violations against a 1% budget -> burning 10x
    assert slo["budget_burn"] == pytest.approx(10.0)
    assert slo["violations"] == {"latency": 1, "shed": 1, "deadline": 0,
                                 "fault": 0}
    reg = telemetry.registry()
    g = reg.gauge("serve.slo_attainment", labels={"model": "slo_tenant"})
    assert g.value == pytest.approx(18 / 20)
    text = m.render_prometheus()
    assert 'lgbm_tpu_serve_slo_budget_burn{model="slo_tenant"}' in text
    assert 'lgbm_tpu_serve_slo_violations_shed{model="slo_tenant"} 1.0' \
        in text


def test_slo_off_keeps_stable_schema():
    m = ServeMetrics()
    snap = m.snapshot()
    assert snap["slo"] is None
    text = m.render_prometheus()
    assert "lgbm_tpu_serve_slo_attainment NaN" in text
    assert "lgbm_tpu_serve_slo_violations_latency NaN" in text


# ------------------------------------------------------------ load generator
def test_arrival_schedule_byte_identical():
    from tools.serve_load import build_schedule, schedule_digest
    a = build_schedule(11, 200.0, 2.0, n_tenants=3,
                       weights=[0.5, 0.3, 0.2], req_max=8, rows=4096)
    b = build_schedule(11, 200.0, 2.0, n_tenants=3,
                       weights=[0.5, 0.3, 0.2], req_max=8, rows=4096)
    assert schedule_digest(a) == schedule_digest(b)
    for key in ("t", "sizes", "offsets", "tenant"):
        assert a[key].tobytes() == b[key].tobytes()
    c = build_schedule(12, 200.0, 2.0, n_tenants=3,
                       weights=[0.5, 0.3, 0.2], req_max=8, rows=4096)
    assert schedule_digest(c) != schedule_digest(a)
    # arrivals start at 0, are sorted, and offer ~target_qps
    assert a["t"][0] == 0.0
    assert (np.diff(a["t"]) >= 0).all()
    assert len(a["t"]) == 400


def test_overloaded_run_queue_wait_dominates_p99():
    """The coordinated-omission acceptance pin: drive an open-loop
    schedule faster than the server can drain (service time padded to a
    known floor) and check queue wait — measured because latency counts
    from the SCHEDULED arrival — dominates p99.  A closed-loop generator
    would never see this: it only issues a request when the previous one
    finishes, so its 'latency' stays near the service time."""
    from tools.serve_load import build_schedule, run_load, summarize

    X, bst = _booster(TRACE_ON, n=2000)
    serve.clear_plan_cache()
    pred = serve.Predictor(bst, name="overload")
    pred.warmup(16)
    real_predict = pred.predict
    service_s = 0.01

    def slowed(Xb, **kw):
        time.sleep(service_s)             # deterministic service floor
        return real_predict(Xb, **kw)

    pred.predict = slowed
    # max_batch 8 rows -> ~3 requests per flush at ~10ms service: the
    # server drains ~300 req/s while 800/s arrive, so the queue grows
    # for the whole run REGARDLESS of host speed (the floor is a sleep)
    mb = serve.MicroBatcher(pred, max_batch=8, max_wait_ms=0.5)
    sched = build_schedule(5, 800.0, 1.0, req_max=4, rows=X.shape[0])
    try:
        result = run_load([mb], X, sched)
    finally:
        mb.close()
        pred.predict = real_predict
    summary = summarize(result, sched, ["overload"])
    assert summary["completed"] == summary["requests"]
    phases = pred.metrics_snapshot()["phases"]
    queue_p99 = phases["queue_wait"]["p99_ms"]
    total_p99 = summary["p99_ms"]
    # queue wait IS the tail: it dwarfs the ~4ms service floor and makes
    # up most of the open-loop p99
    assert total_p99 > 10 * service_s * 1e3, summary
    assert queue_p99 > 0.5 * total_p99, (queue_p99, total_p99, phases)
    assert queue_p99 > 5 * phases["dispatch"]["p99_ms"], phases
    # the driver itself kept pace: lateness is queueing, not submit lag
    assert summary["submit_lag_p99_ms"] < 0.5 * total_p99, summary


def test_serve_load_cli_blob_and_gate(tmp_path):
    """CLI smoke: the extended BENCH_serve blob carries every load-gate
    field, reproducibly-digested schedule included, and
    tools/bench_compare.py extracts the new watched metrics from it."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   [ROOT] + os.environ.get("PYTHONPATH",
                                           "").split(os.pathsep)))
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "serve_load.py"),
         "--qps", "40", "--duration", "1.0", "--rows", "900",
         "--iters", "2", "--tenants", "2", "--weights", "0.7,0.3",
         "--request-log"],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    blob = None
    for line in r.stdout.splitlines():
        if line.startswith("{"):
            obj = json.loads(line)
            if obj.get("metric") == "BENCH_serve":
                blob = obj
    assert blob is not None, r.stdout
    assert blob["mode"] == "load"
    assert blob["offered_qps"] > 0 and blob["achieved_qps"] > 0
    assert blob["p999_ms"] >= blob["p99_ms"] >= blob["p50_ms"]
    assert set(blob["per_tenant"]) == {"t0", "t1"}
    for tb in blob["per_tenant"].values():
        assert tb["requests"] > 0
    assert len(blob["detail"]["schedule_sha256"]) == 64
    assert blob["detail"]["phases"]["t0"]["queue_wait"]["count"] > 0
    assert blob["detail"]["cpu_fallback"] is True
    from tools.bench_compare import extract_metrics
    m = extract_metrics(blob)
    assert m["serve_achieved_qps"] == blob["achieved_qps"]
    assert m["serve_p999_ms"] == blob["p999_ms"]
    assert m["serve_p99_ms"] == blob["p99_ms"]


# -------------------------------------------------------- telemetry report
def test_telemetry_report_serve_cli(tmp_path):
    """--serve replays serve.request events from the SAME JSONL artifact
    the other report tools read into phase + tenant tables (subprocess,
    unknown-kind tolerance preserved)."""
    log = str(tmp_path / "serve_report.jsonl")
    X, bst = _booster(TRACE_ON)
    serve.clear_plan_cache()
    pred = serve.Predictor(bst, name="report_tenant")
    telemetry.configure_log(log)
    try:
        for _ in range(5):
            pred.predict(X[:16])
    finally:
        telemetry.close_log()
    with open(log, "a") as fh:   # unknown kinds must stay tolerated
        fh.write(json.dumps({"schema": 99, "kind": "future.kind",
                             "ts": 1.0}) + "\n")
    r = subprocess.run(
        [sys.executable,
         os.path.join(ROOT, "tools", "telemetry_report.py"),
         "--serve", log],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "serve request phases" in r.stdout
    assert "serve tenants" in r.stdout
    assert "report_tenant" in r.stdout
    for phase in ("queue_wait", "assemble", "dispatch", "post", "total"):
        assert phase in r.stdout
    assert "skipped lines" in r.stdout     # the unknown-schema line
