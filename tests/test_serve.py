"""lightgbm_tpu.serve: compiled inference serving.

Pins the subsystem's contract (ISSUE 2 acceptance criteria):
- device binning bitwise-equal to the host ``BinnedData.apply`` path
  (dense / NaN / categorical / zero_as_missing / f64-only boundary cases),
- ``serve.Predictor`` bitwise-equal to ``Booster.predict``'s device path
  (incl. NaN + categorical + multiclass),
- <= 6 XLA compiles over 20 distinct warm batch sizes (bucket ladder),
- zero re-stacking/re-upload on repeat calls (plan cache hit counter),
- the microbatcher returns exactly what direct predicts would,
- the native-cutoff config knob (env var still overrides).

A module-scoped booster/plan is shared by the read-only tests (XLA:CPU
compile time dominates; one plan serves them all through the cache);
tests that mutate the model or assert cache counters run LAST and clear
the cache explicitly.
"""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import serve
from lightgbm_tpu.binning import bin_dataset, find_bin

pytestmark = pytest.mark.serve
from lightgbm_tpu.serve.bucketing import BucketLadder
from lightgbm_tpu.serve.device_binning import (bin_rows_device,
                                               build_bin_tables, float_bits)


def _device_path(monkeypatch):
    """Force Booster.predict onto the LEGACY device path (no serve routing,
    no native traversal) — the pre-existing numerics serve must match."""
    monkeypatch.setenv("LIGHTGBM_TPU_SERVE", "0")
    monkeypatch.setenv("LIGHTGBM_TPU_NATIVE_PREDICT_MAX_ROWS", "0")


def _messy_data(n=1600, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f) * np.array([1.0, 50.0, 1e-3, 1e5, 1.0, 1.0])[:f]
    X[rng.rand(n, f) < 0.08] = np.nan
    if f > 4:
        X[:, 4] = rng.randint(0, 9, n)
        X[rng.rand(n) < 0.04, 4] = 777    # unseen at predict for some rows
    y = (X[:, 0] + np.nan_to_num(X[:, 1]) / 50.0 > 0).astype(np.float64)
    return X, y


P = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
     "verbosity": -1, "categorical_feature": "4"}


@pytest.fixture(scope="module")
def messy():
    return _messy_data()


@pytest.fixture(scope="module")
def bst(messy):
    X, y = messy
    return lgb.train(P, lgb.Dataset(X, label=y), 8)


# ------------------------------------------------------------ device binning
def test_device_binning_bitwise_messy(messy):
    X, _ = messy
    binned = bin_dataset(X, max_bin=63, categorical_features=[4])
    tables = build_bin_tables(binned.mappers)
    hi, lo = float_bits(X)
    import jax.numpy as jnp
    dev = np.asarray(bin_rows_device(tables, jnp.asarray(hi),
                                     jnp.asarray(lo)))
    np.testing.assert_array_equal(binned.apply(X).astype(np.int32), dev)


def test_device_binning_bitwise_boundaries():
    """Values distinguishable from a bound only in f64 (nextafter), +-0,
    subnormals, inf — the cases an f32 device searchsorted would misbin."""
    rng = np.random.RandomState(1)
    X = rng.randn(4000, 2)
    binned = bin_dataset(X, max_bin=127)
    m = binned.mappers[0]
    vals = []
    for u in m.upper_bounds[:-1]:
        vals += [u, np.nextafter(u, -np.inf), np.nextafter(u, np.inf)]
    vals += [0.0, -0.0, 1e-300, -1e-300, 5e-324, np.inf, -np.inf, np.nan]
    T = np.zeros((len(vals), 2))
    T[:, 0] = vals
    tables = build_bin_tables(binned.mappers)
    hi, lo = float_bits(T)
    import jax.numpy as jnp
    dev = np.asarray(bin_rows_device(tables, jnp.asarray(hi),
                                     jnp.asarray(lo)))
    np.testing.assert_array_equal(binned.apply(T).astype(np.int32), dev)


def test_device_binning_zero_as_missing():
    rng = np.random.RandomState(2)
    X = rng.randn(2000, 3)
    X[rng.rand(2000, 3) < 0.3] = 0.0
    X[rng.rand(2000, 3) < 0.05] = 5e-36   # inside the kZeroThreshold band
    binned = bin_dataset(X, max_bin=31, zero_as_missing=True)
    tables = build_bin_tables(binned.mappers)
    hi, lo = float_bits(X)
    import jax.numpy as jnp
    dev = np.asarray(bin_rows_device(tables, jnp.asarray(hi),
                                     jnp.asarray(lo)))
    np.testing.assert_array_equal(binned.apply(X).astype(np.int32), dev)


def test_device_binning_categorical_edges():
    """Host LUT semantics: truncate toward zero, negative/huge/non-finite
    -> last bin; fractional codes match their truncation."""
    rng = np.random.RandomState(3)
    X = np.zeros((14, 2))
    X[:, 1] = rng.randn(14)
    X[:, 0] = [3.0, 3.9, -0.5, 0.4, 7.0, 8.0, 2.0 ** 31, 2.0 ** 40,
               1e18, -4.0, np.nan, np.inf, -np.inf, 6.0]
    train = np.zeros((500, 2))
    train[:, 0] = rng.randint(0, 9, 500)
    train[:, 1] = rng.randn(500)
    binned = bin_dataset(train, max_bin=31, categorical_features=[0])
    tables = build_bin_tables(binned.mappers)
    hi, lo = float_bits(X)
    import jax.numpy as jnp
    with np.errstate(invalid="ignore"):
        host = binned.apply(X).astype(np.int32)
    dev = np.asarray(bin_rows_device(tables, jnp.asarray(hi),
                                     jnp.asarray(lo)))
    np.testing.assert_array_equal(host, dev)


# -------------------------------------------------------- predictor parity
def test_predictor_bitwise_vs_booster_device_path(messy, bst, monkeypatch):
    X, _ = messy
    pred = serve.Predictor(bst)
    got = pred.predict(X[:700])
    raw = serve.Predictor(bst, raw_score=True).predict(X[:700])
    _device_path(monkeypatch)
    np.testing.assert_array_equal(got, bst.predict(X[:700]))
    np.testing.assert_array_equal(raw, bst.predict(X[:700], raw_score=True))


def test_predictor_bitwise_multiclass(monkeypatch):
    rng = np.random.RandomState(4)
    X = rng.randn(1200, 5)
    X[rng.rand(1200, 5) < 0.05] = np.nan
    y = rng.randint(0, 3, 1200)
    bst3 = lgb.train({"objective": "multiclass", "num_class": 3,
                      "num_leaves": 7, "verbosity": -1},
                     lgb.Dataset(X, label=y), 6)
    got = serve.Predictor(bst3).predict(X[:333])
    assert got.shape == (333, 3)
    _device_path(monkeypatch)
    np.testing.assert_array_equal(got, bst3.predict(X[:333]))


def test_predictor_matches_native_path_closely(messy, bst):
    """The small-batch native path accumulates in f64 — not bitwise, but
    the serve scores must agree to f32 rounding."""
    X, _ = messy
    got = serve.Predictor(bst, raw_score=True).predict(X[:500])
    ref = bst.predict(X[:500], raw_score=True)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_predictor_iteration_slice(messy, bst, monkeypatch):
    X, _ = messy
    got = serve.Predictor(bst, raw_score=True, num_iteration=4,
                          start_iteration=2).predict(X[:200])
    _device_path(monkeypatch)
    ref = bst.predict(X[:200], raw_score=True, num_iteration=4,
                      start_iteration=2)
    np.testing.assert_array_equal(got, ref)


def test_predictor_sparse_input():
    sp = pytest.importorskip("scipy.sparse")
    rng = np.random.RandomState(5)
    X = rng.randn(1200, 8) * (rng.rand(1200, 8) < 0.3)
    y = (X[:, 0] > 0).astype(np.float64)
    bsp = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 6)
    pred = serve.Predictor(bsp)
    got = pred.predict(sp.csr_matrix(X[:400]))
    np.testing.assert_array_equal(got, pred.predict(X[:400]))


def test_predictor_untrained_booster_and_empty_batch():
    X, y = _messy_data(n=400)
    b0 = lgb.Booster(params=dict(P), train_set=lgb.Dataset(X, label=y))
    pred = serve.Predictor(b0, raw_score=True)
    out = pred.predict(X[:10])
    np.testing.assert_allclose(out, np.full(10, b0._gbdt.init_scores[0]))
    assert pred.predict(X[:0]).shape == (0,)


# -------------------------------------------------- compile + cache budgets
def test_compile_budget_20_batch_sizes(messy, bst):
    """<= 6 XLA compiles across 20 distinct batch sizes in [1, 1024]: the
    geometric ladder (base 32, ratio 2) has exactly 6 rungs there."""
    X, _ = messy
    pred = serve.Predictor(bst)
    rng = np.random.RandomState(6)
    sizes = rng.choice(np.arange(1, 1025), size=20, replace=False)
    for s in sizes:
        pred.predict(X[: int(s)])
    assert pred.plan.compile_count() <= 6, pred.metrics_snapshot()
    snap = pred.metrics_snapshot()
    assert snap["requests"] == 20
    assert snap["p50_ms"] is not None


def test_bucket_ladder():
    lad = BucketLadder(base=32, ratio=2)
    assert lad.bucket(1) == 32
    assert lad.bucket(32) == 32
    assert lad.bucket(33) == 64
    assert lad.bucket(1000) == 1024
    assert lad.rungs_upto(1024) == [32, 64, 128, 256, 512, 1024]
    assert lad.max_compiles(1024) == 6
    # one-shot bulk batches above exact_above take their EXACT shape —
    # no ratio-factor padding blowup on multi-million-row predicts
    assert lad.bucket(lad.exact_above + 12345) == lad.exact_above + 12345
    with pytest.raises(ValueError):
        BucketLadder(base=0)


# ------------------------------------------------------------- microbatcher
def test_microbatcher_coalesces_and_matches(messy, bst):
    X, _ = messy
    pred = serve.Predictor(bst)
    ref = pred.predict(X[:60])
    mb = pred.batcher(max_batch=64, max_wait_ms=20)
    futs = [mb.submit(X[i:i + 3]) for i in range(0, 60, 3)]
    got = np.concatenate([f.result(timeout=60) for f in futs])
    mb.close()
    np.testing.assert_array_equal(got, ref)
    snap = pred.metrics_snapshot()
    assert snap["requests"] >= 21            # 20 coalesced + 1 direct
    assert snap["batches"] >= 2
    assert snap["max_queue_depth"] >= 1
    with pytest.raises(RuntimeError):
        mb.submit(X[:1])


def test_predictor_rejects_unsupported():
    X, y = _messy_data(n=600, f=4)
    blin = lgb.train(dict(P, linear_tree=True, categorical_feature=""),
                     lgb.Dataset(X, label=y), 3)
    with pytest.raises(ValueError, match="linear"):
        serve.Predictor(blin)
    loaded = lgb.Booster(model_str=lgb.train(
        {"objective": "binary", "num_leaves": 7, "verbosity": -1},
        lgb.Dataset(X, label=y), 3).model_to_string())
    with pytest.raises(ValueError, match="dataset-backed"):
        serve.Predictor(loaded)


# ------------------------------------------------- forced-bound zero filter
def test_forced_bounds_near_zero_filtered():
    """Satellite (ADVICE round 5): forced bounds within kZeroThreshold
    (1e-35) of zero are dropped, as the reference
    FindBinWithPredefinedBin skips |bound| <= kZeroThreshold."""
    rng = np.random.RandomState(7)
    v = rng.randn(5000)
    base = find_bin(v, 16, forced_upper_bounds=[0.5])
    for z in (0.0, 1e-36, -1e-36, 1e-35, -1e-35):
        m = find_bin(v, 16, forced_upper_bounds=[z, 0.5])
        np.testing.assert_array_equal(m.upper_bounds, base.upper_bounds)
    # a bound OUTSIDE the band is honored
    kept = find_bin(v, 16, forced_upper_bounds=[1e-30, 0.5])
    assert 1e-30 in kept.upper_bounds


# -------------------- cache-counter tests (mutate global cache: run LAST)
def test_plan_cache_no_restack(messy, bst, monkeypatch):
    """Repeat Booster.predict calls routed through the plan must reuse ONE
    build (no re-stacking / re-upload), asserted via the cache counters."""
    monkeypatch.setenv("LIGHTGBM_TPU_NATIVE_PREDICT_MAX_ROWS", "0")
    X, _ = messy
    serve.clear_plan_cache()
    for _ in range(5):
        bst.predict(X[:300])
    stats = serve.cache_stats()
    assert stats["builds"] == 1
    assert stats["hits"] == 4
    plan = serve.plan_for_model(bst._gbdt)
    assert plan.stack_count == 1


def test_native_cutoff_config_knob(monkeypatch):
    """tpu_native_predict_max_rows=0 routes everything to the device plan;
    the env var, where set, overrides the knob."""
    monkeypatch.delenv("LIGHTGBM_TPU_NATIVE_PREDICT_MAX_ROWS", raising=False)
    X, y = _messy_data(n=800, f=4)
    bk = lgb.train({"objective": "binary", "num_leaves": 15,
                    "verbosity": -1, "tpu_native_predict_max_rows": 0},
                   lgb.Dataset(X, label=y), 4)
    assert bk._gbdt._native_predict_cutoff() == 0
    serve.clear_plan_cache()
    ref = bk.predict(X[:100], raw_score=True)
    assert serve.cache_stats()["builds"] == 1     # device plan was used
    # env override wins over the config knob
    monkeypatch.setenv("LIGHTGBM_TPU_NATIVE_PREDICT_MAX_ROWS", "12345")
    assert bk._gbdt._native_predict_cutoff() == 12345
    np.testing.assert_allclose(bk.predict(X[:100], raw_score=True), ref,
                               rtol=1e-5, atol=1e-6)


def test_plan_invalidation_on_leaf_mutation(messy, bst, monkeypatch):
    """In-place leaf rewrites (C-API SetLeafValue/Refit) change neither
    iter_ nor num_trees — the _pred_version bump must still invalidate
    cached plans so the device pack is rebuilt with the new leaf."""
    import types
    from lightgbm_tpu.capi.bridge import (booster_get_leaf_value,
                                          booster_set_leaf_value)
    monkeypatch.setenv("LIGHTGBM_TPU_NATIVE_PREDICT_MAX_ROWS", "0")
    X, _ = messy
    serve.clear_plan_cache()
    # full training matrix: leaf 0 of tree 0 is guaranteed populated there
    before = bst.predict(X, raw_score=True)
    handle = types.SimpleNamespace(bst=bst)
    old = booster_get_leaf_value(handle, 0, 0)
    booster_set_leaf_value(handle, 0, 0, old + 5.0)
    try:
        after = bst.predict(X, raw_score=True)
        assert serve.cache_stats()["builds"] == 2    # plan was rebuilt
        diff = after - before
        assert np.count_nonzero(diff) > 0
        assert np.abs(diff[diff != 0] - 5.0).max() < 1e-5
    finally:
        booster_set_leaf_value(handle, 0, 0, old)
    np.testing.assert_array_equal(bst.predict(X, raw_score=True), before)


def test_plan_invalidation_on_update_and_rollback(messy, bst, monkeypatch):
    monkeypatch.setenv("LIGHTGBM_TPU_NATIVE_PREDICT_MAX_ROWS", "0")
    X, _ = messy
    serve.clear_plan_cache()
    p8 = bst.predict(X[:100], raw_score=True)
    assert serve.cache_stats()["builds"] == 1
    bst.update()                       # +1 round -> new key, rebuild
    p9 = bst.predict(X[:100], raw_score=True)
    assert serve.cache_stats()["builds"] == 2
    assert not np.allclose(p8, p9)
    bst.rollback_one_iter()            # back to 8 rounds -> another key
    p8b = bst.predict(X[:100], raw_score=True)
    assert serve.cache_stats()["builds"] == 3   # _pred_version bumped
    np.testing.assert_array_equal(p8, p8b)
    # rollback + RETRAIN revisits (iter_, num_trees) = (9, 9): without the
    # rollback version bump this would cache-hit the stale pre-rollback
    # pack; the bump forces a fresh build.
    bst.update()
    bst.predict(X[:100], raw_score=True)
    assert serve.cache_stats()["builds"] == 4


def test_running_predictor_hot_swaps_on_model_mutation(messy):
    """End-to-end hot-swap (ISSUE-13 satellite): an ALREADY-CONSTRUCTED
    Predictor must never keep serving a stale pack after its model
    mutates — continued training, rollback, or a refit.  The plan-cache
    key tests above only cover plan_for_model; this pins the Predictor's
    per-request freshness check (the stale-pack hole it closes)."""
    X, y = messy
    params = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
              "min_data_in_leaf": 5}
    bst = lgb.train(params, lgb.Dataset(X, label=y, params=params),
                    num_boost_round=3)
    pred = serve.Predictor(bst, raw_score=True)
    q = X[:64]
    out1 = pred.predict(q)
    np.testing.assert_array_equal(out1, pred.plan.raw_scores(q)[:, 0])
    assert pred.metrics.plan_swaps == 0
    # continued training on the SAME booster object
    bst.update()
    out2 = pred.predict(q)
    assert pred.metrics.plan_swaps == 1
    assert not np.array_equal(out1, out2)
    np.testing.assert_array_equal(
        out2, serve.Predictor(bst, raw_score=True).predict(q))
    # rollback swaps again (state changed, _pred_version bumped)
    bst.rollback_one_iter()
    out3 = pred.predict(q)
    assert pred.metrics.plan_swaps == 2
    np.testing.assert_array_equal(out3, out1)
    # an unchanged model pays NO further swaps (three int compares only)
    pred.predict(q)
    assert pred.metrics.plan_swaps == 2
    # a refit lands via swap_model (new booster object, new leaf values)
    refit = bst.refit(X, np.asarray(y) + 1.0, decay_rate=0.3)
    pred.swap_model(refit)
    out4 = pred.predict(q)
    assert pred.metrics.model_swaps == 1
    assert not np.array_equal(out4, out3)
    snap = pred.metrics_snapshot()
    assert snap["plan_swaps"] == 2 and snap["model_swaps"] == 1
