"""Sorted many-vs-many categorical splits + newly-live split knobs.

Reference: ``FindBestThresholdCategoricalInner`` sorted branch
(``src/treelearner/feature_histogram.cpp:241-340``) — bins sorted by
``grad/(hess+cat_smooth)``, prefix scan from both ends capped at
``max_cat_threshold``, ``min_data_per_group`` grouping, ``l2+cat_l2``
regularization; plus ``path_smooth``, ``extra_trees``,
``feature_fraction_bynode`` (reference ColSampler / USE_RAND scans).
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.split import SplitConfig, best_split

import jax.numpy as jnp


def _cat_data(n=4000, n_cat=40, seed=5):
    """High-cardinality categorical whose optimal partition is a SET of
    categories (many-vs-many) — one-hot (single category vs rest) captures
    only a fraction of the signal per split."""
    rng = np.random.RandomState(seed)
    cat = rng.randint(0, n_cat, size=n)
    # random half of the categories carry +2, the rest -2
    lift = np.where((np.arange(n_cat) * 2654435761 % 97) % 2 == 0, 2.0, -2.0)
    y = lift[cat] + 0.3 * rng.randn(n)
    noise = rng.randn(n, 2)
    X = np.column_stack([cat.astype(np.float64), noise])
    return X, y, lift


BASE = {"objective": "regression", "num_leaves": 8, "learning_rate": 0.5,
        "min_data_in_leaf": 5, "min_data_per_group": 5, "cat_smooth": 1.0,
        "verbosity": -1, "metric": "l2", "deterministic": True}


def _fit_mse(params, X, y, rounds=8):
    bst = lgb.train(params, lgb.Dataset(X, label=y,
                                        categorical_feature=[0]), rounds)
    return float(np.mean((bst.predict(X) - y) ** 2)), bst


def test_sorted_beats_onehot_high_cardinality():
    X, y, _ = _cat_data()
    mse_sorted, bst = _fit_mse(dict(BASE, max_cat_to_onehot=1,
                                    max_cat_threshold=32), X, y)
    mse_onehot, _ = _fit_mse(dict(BASE, max_cat_to_onehot=256), X, y)
    assert mse_sorted < mse_onehot * 0.7, (mse_sorted, mse_onehot)
    # the model must contain multi-category masks (num_cat-style splits)
    dump = bst.dump_model()

    def cat_sizes(node, out):
        if "split_index" in node:
            if node["decision_type"] == "==":
                out.append(len(str(node["threshold"]).split("||")))
            cat_sizes(node["left_child"], out)
            cat_sizes(node["right_child"], out)
    sizes = []
    for info in dump["tree_info"]:
        cat_sizes(info["tree_structure"], sizes)
    assert sizes and max(sizes) > 1, sizes


def test_sorted_cat_round_trip(tmp_path):
    X, y, _ = _cat_data(n=2000, n_cat=25)
    _, bst = _fit_mse(dict(BASE, max_cat_to_onehot=1), X, y, rounds=5)
    p = bst.predict(X)
    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    re = lgb.Booster(model_file=path)
    np.testing.assert_allclose(re.predict(X), p, rtol=1e-5, atol=1e-6)


def test_max_cat_threshold_caps_set_size():
    X, y, _ = _cat_data()
    _, bst = _fit_mse(dict(BASE, max_cat_to_onehot=1, max_cat_threshold=3),
                      X, y)
    dump = bst.dump_model()

    def sizes(node, out):
        if "split_index" in node:
            if node["decision_type"] == "==":
                out.append(len(str(node["threshold"]).split("||")))
            sizes(node["left_child"], out)
            sizes(node["right_child"], out)
    ss = []
    for info in dump["tree_info"]:
        sizes(info["tree_structure"], ss)
    assert ss and max(ss) <= 3, ss


def _root_split(hist_G, hist_H, hist_C, cfg, n_bins):
    f, b = hist_G.shape
    hist = jnp.stack([jnp.asarray(hist_G), jnp.asarray(hist_H),
                      jnp.asarray(hist_C)], axis=-1)
    return best_split(
        hist, jnp.sum(hist[..., 0]), jnp.sum(hist[..., 1]),
        jnp.sum(hist[..., 2]),
        num_bins_per_feature=jnp.full(f, n_bins, jnp.int32),
        nan_bins=jnp.full(f, b, jnp.int32),
        is_categorical=jnp.ones(f, bool),
        monotone=None,
        feature_mask=jnp.ones(f, bool),
        cfg=cfg)


def _toy_hist(b=16):
    """One categorical feature, clear two-sided structure."""
    rng = np.random.RandomState(0)
    G = np.linspace(-5, 5, b)[None, :].astype(np.float32)
    H = np.full((1, b), 10.0, np.float32)
    C = np.full((1, b), 20.0, np.float32)
    return G, H, C


def test_cat_smooth_filters_small_bins():
    G, H, C = _toy_hist()
    base = dict(min_data_in_leaf=1, min_sum_hessian_in_leaf=1e-3,
                max_cat_to_onehot=1, min_data_per_group=1, cat_l2=0.0)
    bs_lo = _root_split(G, H, C, SplitConfig(cat_smooth=1.0, **base), 16)
    # cat_smooth above every bin count -> no sorted bins -> no cat split
    bs_hi = _root_split(G, H, C, SplitConfig(cat_smooth=1000.0, **base), 16)
    assert float(bs_lo.gain) > 0
    assert not bool(bs_hi.is_cat) or float(bs_hi.gain) == float("-inf")
    # and a middle value changes which bins participate
    C2 = C.copy()
    C2[0, :4] = 3.0  # below cat_smooth=5
    bs_mid = _root_split(G, H, C2, SplitConfig(cat_smooth=5.0, **base), 16)
    mask = np.asarray(bs_mid.cat_mask)
    assert not mask[:4].any()  # filtered bins cannot be routed left


def test_min_data_per_group_changes_candidates():
    G, H, C = _toy_hist()
    base = dict(min_data_in_leaf=1, min_sum_hessian_in_leaf=1e-3,
                max_cat_to_onehot=1, cat_smooth=1.0, cat_l2=0.0)
    bs_small = _root_split(G, H, C, SplitConfig(min_data_per_group=1, **base), 16)
    bs_big = _root_split(G, H, C, SplitConfig(min_data_per_group=60, **base), 16)
    # with a 60-row group floor each bin holds 20 rows, so candidate left
    # sets quantize to multiples of 3 bins — the unrestricted optimum (8
    # bins) is no longer reachable and the chosen set changes
    n_small = int(np.asarray(bs_small.cat_mask).sum())
    n_big = int(np.asarray(bs_big.cat_mask).sum())
    assert n_small == 8
    assert n_big != n_small and n_big % 3 == 0
    assert float(bs_big.gain) <= float(bs_small.gain)


def test_path_smooth_blends_towards_parent_output():
    """Single split: smoothed leaf value must equal
    w*(n/s)/(n/s+1) + parent/(n/s+1) (reference CalculateSplittedLeafOutput
    smoothing blend); the root's output is ~0 after boost-from-average."""
    rng = np.random.RandomState(7)
    X = rng.randn(1000, 1)
    y = np.where(X[:, 0] > 0, 2.0, -1.0) + 0.1 * rng.randn(1000)
    p = {"objective": "regression", "num_leaves": 2, "learning_rate": 1.0,
         "min_data_in_leaf": 5, "verbosity": -1, "boost_from_average": True}
    ps = 50.0

    def leaf_stats(bst):
        t = bst.dump_model()["tree_info"][0]["tree_structure"]
        ls = _leaves(t)
        return {n["leaf_index"]: (n["leaf_value"], n["leaf_count"])
                for n in ls}
    plain = leaf_stats(lgb.train(p, lgb.Dataset(X, label=y), 1))
    smooth = leaf_stats(lgb.train(dict(p, path_smooth=ps),
                                  lgb.Dataset(X, label=y), 1))
    assert plain.keys() == smooth.keys() and len(plain) == 2
    for li in plain:
        w, n = plain[li]
        ws, ns = smooth[li]
        assert n == ns  # same structure
        ratio = n / ps
        expect = w * ratio / (ratio + 1.0)  # parent output ~ 0
        np.testing.assert_allclose(ws, expect, rtol=1e-3, atol=1e-3)
    # extreme smoothing pins outputs to the parent (~0)
    huge = leaf_stats(lgb.train(dict(p, path_smooth=1e6),
                                lgb.Dataset(X, label=y), 1))
    for li in huge:
        assert abs(huge[li][0]) < 1e-2


def _leaves(node):
    if "leaf_index" in node:
        return [node]
    return _leaves(node["left_child"]) + _leaves(node["right_child"])


def test_extra_trees_randomizes_thresholds():
    rng = np.random.RandomState(2)
    X = rng.randn(1500, 6)
    y = X @ rng.randn(6) + 0.1 * rng.randn(1500)
    p = {"objective": "regression", "num_leaves": 15, "min_data_in_leaf": 5,
         "verbosity": -1, "deterministic": True}
    det, _ = _bst_mse(p, X, y)
    et1, _ = _bst_mse(dict(p, extra_trees=True, extra_seed=1), X, y)
    et2, _ = _bst_mse(dict(p, extra_trees=True, extra_seed=9), X, y)
    # extra randomness cannot beat exhaustive search on train MSE and
    # different seeds give different models
    assert det <= et1 + 1e-9
    assert et1 != et2
    # still learns
    assert et1 < np.var(y) * 0.5


def _bst_mse(params, X, y, rounds=10):
    bst = lgb.train(params, lgb.Dataset(X, label=y), rounds)
    return float(np.mean((bst.predict(X) - y) ** 2)), bst


def test_feature_fraction_bynode():
    rng = np.random.RandomState(4)
    X = rng.randn(1200, 8)
    y = X[:, 0] * 3 + 0.1 * rng.randn(1200)  # one dominant feature
    p = {"objective": "regression", "num_leaves": 15, "min_data_in_leaf": 5,
         "verbosity": -1, "deterministic": True}
    _, full = _bst_mse(p, X, y, rounds=3)
    _, bynode = _bst_mse(dict(p, feature_fraction_bynode=0.3,
                              feature_fraction_seed=3), X, y, rounds=3)
    # with per-node sampling some nodes must split on non-dominant features
    def feats(bst):
        out = []
        for t in bst.dump_model()["tree_info"]:
            def walk(nd):
                if "split_index" in nd:
                    out.append(nd["split_feature"])
                    walk(nd["left_child"]); walk(nd["right_child"])
            walk(t["tree_structure"])
        return out
    f_full = feats(full)
    f_bynode = feats(bynode)
    assert set(f_full) == {0}
    assert len(set(f_bynode)) > 1
