"""Quantized serving packs + fused Pallas traversal + AOT compile cache
(ISSUE-12).  Pins the subsystem's three contracts:

- **fp32 parity**: quantized predictions sit inside the analytic bound
  ``num_trees * scale / 2`` (the training-AUC-parity-pin style harness),
  across dense / sparse / multiclass-softmax / NaN-missing / categorical
  inputs — and the ROUTING is exact, witnessed by an independent numpy
  walker over the quantized pack matching the device path integer-for-
  integer;
- **fused == unfused, bitwise, unconditionally**: integer accumulation
  over the same pack cannot regroup, pinned across the shape-bucket
  ladder (interpret-mode kernel on CPU — tier-1 runs the kernel body);
- **zero cold-start**: a simulated process restart against a warm AOT
  cache dir pays zero XLA compiles and answers bitwise-identically;
  corrupt and version-stale entries are detected, warned about and
  rebuilt (the PR-6 checksummed-frame discipline).
"""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import serve
from lightgbm_tpu.models.tree import (QUANT_BITS, quantize_error_bound,
                                      quantize_stack_trees, tree_max_depth)

pytestmark = pytest.mark.serve


def _messy_data(n=1600, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f) * np.array([1.0, 50.0, 1e-3, 1e5, 1.0, 1.0])[:f]
    X[rng.rand(n, f) < 0.08] = np.nan
    if f > 4:
        X[:, 4] = rng.randint(0, 9, n)
        X[rng.rand(n) < 0.04, 4] = 777    # unseen at predict for some rows
    y = (X[:, 0] + np.nan_to_num(X[:, 1]) / 50.0 > 0).astype(np.float64)
    return X, y


P = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
     "verbosity": -1, "categorical_feature": "4"}


@pytest.fixture(scope="module")
def messy():
    return _messy_data()


@pytest.fixture(scope="module")
def bst(messy):
    X, y = messy
    return lgb.train(P, lgb.Dataset(X, label=y), 8)


# ------------------------------------------------------------ fp32 parity
@pytest.mark.parametrize("mode", ["int16", "int8"])
def test_parity_dense_messy(messy, bst, mode):
    """Dense + NaN + categorical(incl. unseen) raw scores inside the
    analytic quantization bound; plan reports the mode it serves with."""
    X, _ = messy
    ref = serve.Predictor(bst, raw_score=True).predict(X[:700])
    pred = serve.Predictor(bst, raw_score=True, quantize=mode)
    assert pred.plan.quantize_mode == mode
    got = pred.predict(X[:700])
    bound = pred.plan.quantize_error_bound()
    assert bound > 0
    assert np.abs(got - ref).max() <= bound + 1e-12
    snap = pred.metrics_snapshot()
    assert snap["quantize"] == mode


@pytest.mark.parametrize("mode", ["int16", "int8"])
def test_parity_sparse(mode):
    sp = pytest.importorskip("scipy.sparse")
    rng = np.random.RandomState(5)
    X = rng.randn(1200, 8) * (rng.rand(1200, 8) < 0.3)
    y = (X[:, 0] > 0).astype(np.float64)
    bsp = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 6)
    pred = serve.Predictor(bsp, raw_score=True, quantize=mode)
    ref = serve.Predictor(bsp, raw_score=True).predict(X[:400])
    got = pred.predict(sp.csr_matrix(X[:400]))
    assert np.abs(got - ref).max() <= pred.plan.quantize_error_bound() + 1e-12
    # sparse and dense route through the SAME pack: bitwise-equal
    np.testing.assert_array_equal(got, pred.predict(X[:400]))


def test_parity_multiclass_softmax():
    """Raw margins inside the bound AND transformed (softmax) outputs
    close — the output transform runs outside the quantized program."""
    rng = np.random.RandomState(4)
    X = rng.randn(1200, 5)
    X[rng.rand(1200, 5) < 0.05] = np.nan
    y = rng.randint(0, 3, 1200)
    bst3 = lgb.train({"objective": "multiclass", "num_class": 3,
                      "num_leaves": 7, "verbosity": -1},
                     lgb.Dataset(X, label=y), 6)
    raw_ref = serve.Predictor(bst3, raw_score=True).predict(X[:333])
    pq = serve.Predictor(bst3, raw_score=True, quantize="int16")
    raw_q = pq.predict(X[:333])
    bound = pq.plan.quantize_error_bound()
    assert np.abs(raw_q - raw_ref).max() <= bound + 1e-12
    soft = serve.Predictor(bst3, quantize="int16").predict(X[:333])
    assert soft.shape == (333, 3)
    np.testing.assert_allclose(soft.sum(axis=1), 1.0, rtol=1e-5)
    ref_soft = serve.Predictor(bst3).predict(X[:333])
    np.testing.assert_allclose(soft, ref_soft, atol=5 * bound + 1e-7)


def _walk_pack_numpy(pack, bins, nan_bins):
    """Independent numpy reference walker over the QUANTIZED pack —
    routing through bit-packed cat masks, NaN default routing and
    sentinel degenerate trees, accumulating int32 quanta.  The device
    paths must match it integer-for-integer (routing exactness)."""
    sf = np.asarray(pack["split_feature"])
    sb = np.asarray(pack["split_bin"])
    dl = np.asarray(pack["default_left"])
    ic = np.asarray(pack["is_cat"])
    cb = np.asarray(pack["cat_bits"])
    lc = np.asarray(pack["left_child"])
    rc = np.asarray(pack["right_child"])
    lq = np.asarray(pack["leaf_q"])
    t = sf.shape[0]
    n = bins.shape[0]
    acc = np.zeros(n, np.int64)
    for ti in range(t):
        for r in range(n):
            node = 0
            while True:
                f = int(sf[ti, node])
                col = int(bins[r, f])
                if ic[ti, node]:
                    go_left = bool((cb[ti, node, col >> 3]
                                    >> (col & 7)) & 1)
                elif col == int(nan_bins[f]):
                    go_left = bool(dl[ti, node])
                else:
                    go_left = col <= int(sb[ti, node])
                nxt = int(lc[ti, node] if go_left else rc[ti, node])
                if nxt < 0:
                    acc[r] += int(lq[ti, ~nxt])
                    break
                node = nxt
    return acc


def test_routing_exact_vs_numpy_walker(messy, bst):
    """The device integer sums equal an independent host walker's —
    quantization moved ONLY the leaf values, never a routing decision
    (categorical edges, NaN defaults and unseen categories included)."""
    import jax.numpy as jnp

    from lightgbm_tpu.models.tree import _ensemble_sum_q

    X, _ = messy
    binned = bst._gbdt.train_data.binned
    bins = binned.apply(X[:200]).astype(np.int32)
    nan_bins = np.asarray(binned.nan_bins)
    trees = bst._gbdt.host_trees()[0]
    pack = quantize_stack_trees(trees, bst._gbdt.cfg.num_leaves,
                                binned.max_num_bins, "int16")
    dev = np.asarray(_ensemble_sum_q(pack, jnp.asarray(bins),
                                     jnp.asarray(nan_bins, jnp.int32)))
    host = _walk_pack_numpy(pack, bins, nan_bins)
    np.testing.assert_array_equal(dev, host.astype(np.int32))


# ------------------------------------------------- pack format + size wins
def test_pack_shrink_ratio_bench_shape():
    """The acceptance-criteria shape (max_bin 255 ensemble): quantized
    serve.plan_bytes >= 3x smaller than fp32."""
    rng = np.random.RandomState(0)
    X = rng.randn(8000, 16)
    X[rng.rand(8000, 16) < 0.02] = np.nan
    y = (X[:, 0] + np.nan_to_num(X[:, 1]) > 0).astype(np.float64)
    b = lgb.train({"objective": "binary", "num_leaves": 31,
                   "verbosity": -1}, lgb.Dataset(X, label=y), 20)
    fp = serve.plan_for_model(b._gbdt, quantize="off")
    for mode in ("int16", "int8"):
        q = serve.plan_for_model(b._gbdt, quantize=mode)
        assert fp.plan_bytes / q.plan_bytes >= 3.0, (
            mode, fp.plan_bytes, q.plan_bytes)


def test_pack_encoding_and_bound(bst):
    """Narrow dtypes, bit-packed cat masks, sentinel degenerate trees,
    and the analytic error bound's shape."""
    g = bst._gbdt
    trees = g.host_trees()[0]
    nb = g.train_data.binned.max_num_bins
    for mode, (dt, qmax) in QUANT_BITS.items():
        pack = quantize_stack_trees(trees, g.cfg.num_leaves, nb, mode)
        assert pack["leaf_q"].dtype == dt
        assert pack["split_feature"].dtype == np.int16
        assert pack["cat_bits"].dtype == np.uint8
        assert pack["cat_bits"].shape[2] == -(-nb // 8)
        assert int(np.abs(np.asarray(pack["leaf_q"])).max()) <= qmax
        assert quantize_error_bound(pack) == \
            len(trees) * pack["scale"] * 0.5
        assert pack["depth"] >= 1
    # shape gate: an impossible encoding returns None (caller degrades)
    assert quantize_stack_trees(trees, 40000, nb, "int16") is None
    assert tree_max_depth(np.zeros(0, np.int32), np.zeros(0, np.int32)) == 1


def test_untrained_and_degenerate_trees(messy):
    """Sentinel-encoded degenerate trees: an untrained booster's quantized
    predictor answers init scores, same as fp32."""
    X, y = _messy_data(n=400)
    b0 = lgb.Booster(params=dict(P), train_set=lgb.Dataset(X, label=y))
    pred = serve.Predictor(b0, raw_score=True, quantize="int16")
    out = pred.predict(X[:10])
    np.testing.assert_allclose(out, np.full(10, b0._gbdt.init_scores[0]))


# ------------------------------------------- fused traversal: bitwise pin
def test_fused_bitwise_unfused_across_ladder(messy, bst):
    """The ISSUE-12 identity criterion: fused (interpret-mode Pallas on
    CPU) == unfused (XLA while-loop walk), bitwise, across ladder rungs
    AND within-rung sizes (1 vs 31 pad onto the same rung; 33/100/512
    span three more) — integer accumulation cannot regroup.  int8
    identity rides test_fused_multiclass_and_sparse_bitwise."""
    X, _ = messy
    fused = serve.Predictor(bst, raw_score=True, quantize="int16",
                            traverse="fused")
    unfused = serve.Predictor(bst, raw_score=True, quantize="int16",
                              traverse="unfused")
    assert fused.plan.traverse_mode == "fused"
    assert unfused.plan.traverse_mode == "unfused"
    for n in (1, 31, 33, 100, 512):
        np.testing.assert_array_equal(fused.predict(X[:n]),
                                      unfused.predict(X[:n]))


def test_fused_multiclass_and_sparse_bitwise():
    sp = pytest.importorskip("scipy.sparse")
    rng = np.random.RandomState(9)
    X = rng.randn(900, 7) * (rng.rand(900, 7) < 0.4)
    X[rng.rand(900, 7) < 0.05] = np.nan
    y = rng.randint(0, 3, 900)
    b3 = lgb.train({"objective": "multiclass", "num_class": 3,
                    "num_leaves": 7, "verbosity": -1},
                   lgb.Dataset(X, label=y), 4)
    kw = dict(raw_score=True, quantize="int8")
    fused = serve.Predictor(b3, traverse="fused", **kw)
    unfused = serve.Predictor(b3, traverse="unfused", **kw)
    np.testing.assert_array_equal(fused.predict(X[:200]),
                                  unfused.predict(X[:200]))
    Xs = sp.csr_matrix(np.nan_to_num(X[:200]))
    np.testing.assert_array_equal(fused.predict(Xs), unfused.predict(Xs))


def test_microbatcher_composes_with_quantized_fused(messy, bst):
    """The quantized/fused plan rides the whole serving stack: coalesced
    microbatcher requests resolve to exactly what direct predicts
    return (plan-cache hit reuses the ladder-pinned programs)."""
    X, _ = messy
    pred = serve.Predictor(bst, raw_score=True, quantize="int16",
                           traverse="fused")
    ref = pred.predict(X[:30])
    mb = pred.batcher(max_batch=32, max_wait_ms=20)
    futs = [mb.submit(X[i:i + 3]) for i in range(0, 30, 3)]
    got = np.concatenate([f.result(timeout=60) for f in futs])
    mb.close()
    np.testing.assert_array_equal(got, ref)


def test_traverse_gates_and_degrade(messy, bst):
    """fused without a quantized pack degrades (warn + reason); auto off
    TPU stays unfused; the VMEM layout gate is monotone in pack size."""
    from lightgbm_tpu.ops.pallas_traverse import (traverse_layout,
                                                  traverse_layout_fits)
    p = serve.Predictor(bst, traverse="fused")           # quantize off
    assert p.plan.traverse_mode == "unfused"
    assert "quantized pack" in (p.plan.traverse_degrade or "")
    p2 = serve.Predictor(bst, quantize="int16")          # auto, CPU
    assert p2.plan.traverse_mode == "unfused"
    assert p2.plan.traverse_degrade is None
    lay = traverse_layout(20, 31, 16, 256)
    assert lay["fits"] and lay["total_bytes"] > 0
    assert not traverse_layout_fits(4000, 4096, 2000, 256)


# --------------------------------------------- AOT compile cache (restart)
def test_aot_cache_zero_cold_start(messy, bst, tmp_path):
    """Simulated restart: second predictor against the warm cache dir
    loads every rung from disk — zero fresh compiles, bitwise-identical
    answers, counters visible in the metrics snapshot."""
    X, _ = messy
    d = str(tmp_path / "aot")
    serve.clear_plan_cache()
    p1 = serve.Predictor(bst, raw_score=True, compile_cache=d)
    r1 = p1.predict(X[:100])
    st1 = p1.plan.aot_stats()
    assert st1["compiles"] >= 1 and st1["hits"] == 0
    assert p1.plan.compile_count() == st1["compiles"]
    entries = [f for f in os.listdir(d) if f.endswith(".aot")]
    assert len(entries) == st1["compiles"]
    serve.clear_plan_cache()                 # "restart"
    p2 = serve.Predictor(bst, raw_score=True, compile_cache=d)
    r2 = p2.predict(X[:100])
    st2 = p2.plan.aot_stats()
    assert st2["compiles"] == 0 and st2["hits"] >= 1
    assert p2.plan.compile_count() == 0      # the zero in zero cold-start
    np.testing.assert_array_equal(r1, r2)
    snap = p2.metrics_snapshot()
    assert snap["aot"]["hits"] >= 1
    serve.clear_plan_cache()


def test_aot_cache_corrupt_entry_rebuilt(messy, bst, tmp_path):
    """A torn/corrupt frame fails the checksum, is unlinked with a
    warning and rebuilt from a fresh compile — requests never fail."""
    X, _ = messy
    d = str(tmp_path / "aot")
    serve.clear_plan_cache()
    p1 = serve.Predictor(bst, raw_score=True, compile_cache=d)
    r1 = p1.predict(X[:64])
    name = next(f for f in os.listdir(d) if f.endswith(".aot"))
    path = os.path.join(d, name)
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(size // 2)
    serve.clear_plan_cache()
    p2 = serve.Predictor(bst, raw_score=True, compile_cache=d)
    r2 = p2.predict(X[:64])
    st = p2.plan.aot_stats()
    assert st["compiles"] == 1 and st["cache"]["errors"] >= 1
    np.testing.assert_array_equal(r1, r2)
    assert os.path.getsize(path) > size // 2     # rebuilt entry republished
    serve.clear_plan_cache()


def test_aot_cache_sweep_stale(tmp_path):
    """Hygiene: sweep_stale keeps loadable entries, drops corrupt and
    version-stale ones."""
    import pickle

    from lightgbm_tpu.serialization import write_atomic_frame
    from lightgbm_tpu.serve.compile_cache import CompileCache

    d = str(tmp_path / "aot")
    cc = CompileCache(d)
    os.makedirs(d, exist_ok=True)
    # corrupt frame
    with open(os.path.join(d, "bad.aot"), "wb") as fh:
        fh.write(b"not a frame")
    # version-stale (valid frame, wrong version tag)
    stale = pickle.dumps(({"versions": {"jax": "0.0.0", "jaxlib": "0.0.0",
                                        "backend": "cpu"}},
                          b"", None, None), protocol=4)
    write_atomic_frame(os.path.join(d, "stale.aot"), stale)
    res = cc.sweep_stale()
    assert res == {"kept": 0, "removed": 2}
    assert not [f for f in os.listdir(d) if f.endswith(".aot")]
    # loading a missing key is a clean miss
    assert cc.load("0" * 64) is None
    assert cc.stats()["misses"] >= 1


def test_quantized_plans_coexist_in_cache(messy, bst):
    """The plan-cache key carries the quantize mode: fp32 and quantized
    plans of one model are distinct entries (per-tenant pack formats)."""
    serve.clear_plan_cache()
    a = serve.plan_for_model(bst._gbdt, quantize="off")
    b = serve.plan_for_model(bst._gbdt, quantize="int8")
    c = serve.plan_for_model(bst._gbdt, quantize="int8")
    assert a is not b and b is c
    assert serve.cache_stats()["builds"] == 2
    assert a.identity != b.identity
    serve.clear_plan_cache()
