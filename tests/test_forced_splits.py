"""Forced splits (reference ``ForceSplits``,
``serial_tree_learner.cpp:620`` + ``forcedsplits_filename``): a JSON tree of
(feature, threshold) is applied from the root before gain-driven growth."""

import json

import numpy as np
import pytest
from sklearn.datasets import make_classification

import lightgbm_tpu as lgb


def test_forced_root_and_nested_child(tmp_path):
    X, y = make_classification(n_samples=2000, n_features=8, n_informative=4,
                               random_state=0)
    spec = {
        "feature": 5, "threshold": 0.25,
        "left": {"feature": 3, "threshold": -0.5},
    }
    path = tmp_path / "forced.json"
    path.write_text(json.dumps(spec))
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "min_data_in_leaf": 5, "verbosity": -1,
                     "forcedsplits_filename": str(path)},
                    lgb.Dataset(X, label=y), 4)
    for tree in bst._gbdt.models[0]:
        # node 0 = forced root; node 1 = forced split of its LEFT child
        assert tree.split_feature[0] == 5
        assert tree.split_feature[1] == 3
        # node 1 must actually be the left child of node 0
        assert tree.left_child[0] == 1
        # forced thresholds bin-quantized around the requested value
        td = bst._gbdt.train_data
        thr0 = td.binned.mappers[5].bin_to_threshold(tree.split_bin[0])
        assert abs(thr0 - 0.25) < 0.2
    # training still learns: accuracy beyond chance
    acc = ((bst.predict(X) > 0.5) == (y > 0.5)).mean()
    assert acc > 0.8


def test_forced_splits_survive_model_roundtrip(tmp_path):
    X, y = make_classification(n_samples=1200, n_features=6, random_state=1)
    path = tmp_path / "forced.json"
    path.write_text(json.dumps({"feature": 2, "threshold": 0.0}))
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "forcedsplits_filename": str(path)}
    bst = lgb.train(params, lgb.Dataset(X, label=y), 3)
    s = bst.model_to_string()
    reloaded = lgb.Booster(model_str=s)
    np.testing.assert_allclose(reloaded.predict(X[:50]), bst.predict(X[:50]),
                               rtol=1e-6)


def test_forced_splits_reject_wave_config(tmp_path):
    X, y = make_classification(n_samples=4000 + 2100, n_features=6,
                               random_state=2)
    path = tmp_path / "forced.json"
    path.write_text(json.dumps({"feature": 0, "threshold": 0.0}))
    # leaf_batch>1 downgrades with a warning rather than erroring
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
                     "tpu_leaf_batch": 8,
                     "forcedsplits_filename": str(path)},
                    lgb.Dataset(X, label=y), 2)
    assert bst._gbdt.models[0][0].split_feature[0] == 0


def test_forced_splits_survive_intermediate_monotone(tmp_path):
    """_inter_refresh overwrites best_* for all leaves at the end of each
    growth step, but _apply_forced re-pins the pending forced directive at
    the START of every step (grower.py body), so forced splits must still
    land under monotone_constraints_method=intermediate."""
    rng = np.random.RandomState(0)
    n = 4000
    X = rng.rand(n, 4).astype(np.float32)
    y = 2 * X[:, 0] + np.sin(5 * X[:, 1]) + 0.5 * X[:, 2] \
        + 0.1 * rng.randn(n)
    path = tmp_path / "forced.json"
    path.write_text(json.dumps({
        "feature": 3, "threshold": 0.5,
        "left": {"feature": 3, "threshold": 0.25}}))
    for method in ("basic", "intermediate"):
        params = {"objective": "regression", "num_leaves": 15,
                  "monotone_constraints": [1, 0, 0, 0],
                  "monotone_constraints_method": method,
                  "forcedsplits_filename": str(path),
                  "min_data_in_leaf": 5, "verbosity": -1}
        bst = lgb.train(params, lgb.Dataset(X, label=y), 2)
        for tree in bst._gbdt.models[0]:
            assert tree.split_feature[0] == 3
            assert tree.left_child[0] == 1
            assert tree.split_feature[1] == 3
