"""Training-health sentinel (ISSUE 8, resilience/health.py).

Pins the subsystem's contract:
- the in-dispatch health vector catches an injected NaN gradient at the
  exact round, under the per-round path AND inside the iter-pack scan
  (surfaced at commit boundaries, K=1 == K=4 trees with the sentinel on),
- ``tpu_health_policy``: off is bitwise-inert, warn logs and continues,
  halt raises :class:`HealthHaltError`, rollback restores the last good
  checkpoint in-process and the recovered model is BITWISE identical to a
  fresh run resumed from that checkpoint with the same recovery salt (the
  acceptance criterion),
- ``tpu_health_max_rollbacks`` caps recovery; rollback without a
  checkpoint escalates instead of looping,
- the divergence detector: non-finite loss (the ``inf_loss`` fault),
  spike-over-trailing-window, bitwise stagnation,
- the promoted quantized int16-wire overflow signal (``overflow_hist``
  fault) reports escalations while the int32 fallback keeps trees exact,
- serve guards: non-finite device scores answer from the host mirror
  (counted in ``ServeMetrics.nan_scores``, incl. multiclass softmax) and
  Inf-laden raw inputs are rejected at the door,
- ingestion validation: non-finite labels/weights, binary/poisson label
  domains, all-NaN / constant feature column warnings.

Every injected failure goes through resilience/faults.py — deterministic,
no real divergence required.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.resilience import checkpoint, faults, health
from lightgbm_tpu.resilience.health import (HealthHaltError,
                                            TrainingHealthSentinel)

pytestmark = pytest.mark.health

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    """No test inherits another's armed faults or overflow tallies."""
    faults.install(None)
    health.reset_overflow()
    yield
    faults.install(None)
    health.reset_overflow()


def _data(n=400, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
    return X, y


BASE = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
        "min_data_in_leaf": 5, "seed": 3}


def _train(params, X, y, rounds=8, **kw):
    return lgb.train(dict(params), lgb.Dataset(X.copy(), label=y.copy()),
                     num_boost_round=rounds, **kw)


def _trees(model_str: str) -> str:
    """The tree sections only — the trailing parameters block echoes
    tpu_health_* knobs, which differ between compared configs by design."""
    return model_str[model_str.index("Tree=0"): model_str.index("end of trees")]


# ------------------------------------------------------------ inert default
def test_policy_off_is_bitwise_inert():
    """policy=off and an explicit warn run grow IDENTICAL trees — the
    guards add no observable numeric change (acceptance criterion)."""
    X, y = _data()
    s_off = _trees(_train(BASE, X, y).model_to_string())
    s_warn = _trees(_train(dict(BASE, tpu_health_policy="warn"), X, y)
                    .model_to_string())
    assert s_off == s_warn


def test_bad_policy_rejected():
    X, y = _data(100, 4)
    with pytest.raises(ValueError, match="tpu_health_policy"):
        _train(dict(BASE, tpu_health_policy="explode"), X, y, rounds=1)


# ------------------------------------------------------- detection policies
def test_nan_grads_halt_per_round():
    X, y = _data()
    faults.install("nan_grads:5")
    with pytest.raises(HealthHaltError, match="grad_nonfinite"):
        _train(dict(BASE, tpu_health_policy="halt", tpu_iter_pack=1), X, y)


def test_nan_grads_halt_packed():
    X, y = _data()
    faults.install("nan_grads:5")
    with pytest.raises(HealthHaltError, match="nonfinite"):
        _train(dict(BASE, tpu_health_policy="halt", tpu_iter_pack=4), X, y)


def test_nan_grads_warn_continues_and_reports():
    X, y = _data()
    faults.install("nan_grads:5")
    bst = _train(dict(BASE, tpu_health_policy="warn", tpu_iter_pack=1), X, y)
    rep = bst._health_report
    assert rep["verdict"] == "tripped"
    assert any("grad_nonfinite" in t for t in rep["trips"])
    assert rep["rollbacks"] == 0


def test_health_report_always_attached():
    X, y = _data(100, 4)
    bst = _train(BASE, X, y, rounds=2)
    assert bst._health_report["policy"] == "off"
    assert bst._health_report["verdict"] == "unchecked"


def test_inf_loss_drives_divergence_detector():
    """The detector path (not the health vector): the model never actually
    diverges, the sentinel just observes an injected inf loss row."""
    X, y = _data()
    faults.install("inf_loss:4")
    with pytest.raises(HealthHaltError, match="nonfinite_loss"):
        _train(dict(BASE, tpu_health_policy="halt",
                    metric="binary_logloss"), X, y,
               valid_sets=[lgb.Dataset(X[:100].copy(),
                                       label=y[:100].copy())])


def test_pack_training_metric_no_false_stagnation():
    """Mid-pack, train scores already hold the whole pack's update, so the
    training metric is the same value at every commit — the sentinel must
    not read that as loss_stagnation on a healthy run (training rows are
    dropped from the detector under packing; valid rows advance per
    commit and stay)."""
    X, y = _data()
    bst = _train(dict(BASE, tpu_health_policy="halt", tpu_iter_pack=8,
                      is_provide_training_metric=True,
                      metric="binary_logloss"), X, y, rounds=16)
    assert bst._health_report["verdict"] == "healthy"
    assert bst._gbdt.iter_ == 16


def test_pack_parity_with_sentinel_active():
    """K=1 == K=4 trees with the sentinel armed: the health carry in the
    scan body is observation-only."""
    X, y = _data()
    p = dict(BASE, tpu_health_policy="warn")
    s1 = _trees(_train(dict(p, tpu_iter_pack=1), X, y).model_to_string())
    s4 = _trees(_train(dict(p, tpu_iter_pack=4), X, y).model_to_string())
    assert s1 == s4


# ------------------------------------------------------------- auto-recovery
def _rollback_params(d, **extra):
    return dict(BASE, tpu_iter_pack=4, checkpoint_interval=4,
                checkpoint_keep=8, checkpoint_dir=d,
                tpu_health_policy="rollback", **extra)


def test_rollback_recovers_bitwise_vs_fresh_resume(tmp_path):
    """THE acceptance criterion: NaN at round 10 of 16 under rollback ->
    restore the iter-8 snapshot in-process, back off lr, re-fold keys,
    finish — and the final model's trees are bitwise identical to a fresh
    run resumed from the same snapshot with tpu_health_recovery_salt=1."""
    d = str(tmp_path / "ck")
    X, y = _data()
    faults.install("nan_grads:10")
    rec = _train(_rollback_params(d), X, y, rounds=16)
    faults.install(None)
    rep = rec._health_report
    assert rep["verdict"] == "recovered"
    assert rep["rollbacks"] == 1
    assert rec._gbdt.iter_ == 16
    assert rec.cfg.learning_rate == pytest.approx(0.05)  # 0.1 * 0.5**1

    snap8 = [p for it, p in checkpoint.list_snapshots(d) if it == 8]
    assert snap8, "iteration-8 snapshot missing"
    fresh = _train(_rollback_params(d, tpu_health_recovery_salt=1), X, y,
                   rounds=16, resume_from=snap8[0])
    assert _trees(rec.model_to_string()) == _trees(fresh.model_to_string())


def test_rollback_exhaustion_escalates(tmp_path):
    """max_rollbacks=0: the first trip has no recovery budget and must
    escalate to HealthHaltError instead of looping."""
    d = str(tmp_path / "ck")
    X, y = _data()
    faults.install("nan_grads:6")
    with pytest.raises(HealthHaltError, match="max_rollbacks"):
        _train(_rollback_params(d, tpu_health_max_rollbacks=0), X, y,
               rounds=8)


def test_rollback_without_checkpoint_halts():
    """rollback policy but checkpoint_interval=0: a trip cannot restore
    anything — clear escalation, not an infinite loop."""
    X, y = _data()
    faults.install("nan_grads:3")
    with pytest.raises(HealthHaltError, match="rollback impossible"):
        _train(dict(BASE, tpu_health_policy="rollback", tpu_iter_pack=1),
               X, y)


def test_halt_error_carries_booster():
    X, y = _data()
    faults.install("nan_grads:3")
    with pytest.raises(HealthHaltError) as ei:
        _train(dict(BASE, tpu_health_policy="halt", tpu_iter_pack=1), X, y)
    bst = ei.value.booster
    assert bst is not None
    # terminal verdict: a halted run must never read as tripped-but-alive
    # (or "recovered", when earlier rollbacks happened) in triage
    assert bst._health_report["verdict"] == "halted"
    assert bst._gbdt.iter_ >= 2   # rounds before the poison committed


# --------------------------------------------------- detector unit behavior
def _sentinel(**over):
    cfg = Config(dict({"tpu_health_policy": "halt", "tpu_health_window": 3,
                       "tpu_health_spike_factor": 10.0}, **over))
    return TrainingHealthSentinel(cfg)


def test_detector_spike():
    s = _sentinel()
    for i, v in enumerate([1.0, 0.9, 0.8, 0.75]):
        assert s.observe_round(i + 1, None,
                               [("valid", "l2", v, False)]) is None
    trip = s.observe_round(5, None, [("valid", "l2", 8.5, False)])
    assert trip is not None and trip.reason == "loss_spike"
    assert s.verdict() == "tripped"


def test_detector_spike_ignores_higher_better():
    s = _sentinel()
    for i, v in enumerate([0.5, 0.6, 0.7, 0.99, 0.99, 0.99]):
        assert s.observe_round(i + 1, None,
                               [("valid", "auc", v, True)]) is None


def test_detector_stagnation():
    s = _sentinel()
    vals = [0.5, 0.4, 0.31, 0.31, 0.31]
    trips = [s.observe_round(i + 1, None, [("valid", "l2", v, False)])
             for i, v in enumerate(vals)]
    assert all(t is None for t in trips[:-1])
    assert trips[-1] is not None and trips[-1].reason == "loss_stagnation"


def test_detector_score_overflow():
    s = _sentinel(tpu_health_score_limit=100.0)
    hv = np.array([0.0, 0.0, 0.0, 0.0, 250.0])
    trip = s.observe_round(1, hv, None)
    assert trip is not None and trip.reason == "score_overflow"


def test_halted_verdict_wins_over_recovered(tmp_path):
    """Exhausted rollbacks: the report must say "halted", not "recovered",
    even though a rollback succeeded earlier (the inf_loss detector keeps
    the spike history clear, so only the once-per-install faults trip)."""
    s = _sentinel()
    s.observe_round(1, np.array([1.0, 0, 0, 0, 0]), None)  # trip
    s.note_rollback(0, 1)
    assert s.verdict() == "recovered"
    s.note_halt()
    assert s.verdict() == "halted"
    assert s.report()["rollbacks"] == 1


def test_pack_trailing_health_survives_commits():
    """A mid-pack degenerate stop (j0 >= 1): the committed rounds' health
    vectors pop first, and the TRIMMED stopping round's vector surfaces
    after them instead of being clobbered by the first commit — the
    plumbing that lets the engine catch a round that grew no tree."""
    X, y = _data()
    params = dict(BASE, tpu_health_policy="warn")
    ds = lgb.Dataset(X.copy(), label=y.copy())
    ds.construct(params)
    bst = lgb.Booster(params=params, train_set=ds)
    g = bst._gbdt
    rounds, _fin = g.train_pack(2)
    assert len(rounds) == 2
    # fabricate the mid-pack-stop shape: one committed round pending plus
    # a distinct trailing (trimmed-round) vector
    g.commit_round(rounds[0])
    committed_hv = np.array(g._pack_health_pending[0], np.float64) \
        if g._pack_health_pending else None
    g._trailing_health = np.array([7.0, 0, 0, 0, 0])
    g.commit_round(rounds[1])
    first = g.consume_health()
    assert first is not None and first[0] == 0.0     # committed round's
    if committed_hv is not None:
        np.testing.assert_array_equal(first, committed_hv)
    trailing = g.consume_health()                    # then the trimmed one
    assert trailing is not None and trailing[0] == 7.0
    assert g.consume_health() is None


def test_detector_healthy_report_schema():
    s = _sentinel()
    s.observe_round(1, np.zeros(5), [("valid", "l2", 0.5, False)])
    rep = s.report()
    assert rep["verdict"] == "healthy"
    assert set(rep) >= {"policy", "verdict", "rounds_checked", "trips",
                        "rollbacks", "overflow_escalations", "last_health"}
    assert rep["last_health"]["grad_nonfinite"] == 0.0


# ----------------------------------------------------- quantized overflow
@pytest.mark.slow
def test_overflow_signal_reports_and_trees_exact():
    """``overflow_hist`` forces every int16-wire decision to escalate: the
    sentinel reports it, and the int32 fallback keeps the trees bitwise
    identical to the unforced run (the guard is exact — the signal is
    triage, not a numeric event)."""
    rng = np.random.RandomState(0)
    n = 8 * 2100                       # past the sharded-perm row floor
    X = rng.rand(n, 6)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
    params = dict(BASE, tree_learner="data",
                  tpu_hist_comm="reduce_scatter", use_quantized_grad=True,
                  tpu_health_policy="warn")
    clean = _train(params, X, y, rounds=3)
    assert clean._health_report["overflow_escalations"] == 0
    health.reset_overflow()
    faults.install("overflow_hist")
    forced = _train(params, X, y, rounds=3)
    assert forced._health_report["overflow_escalations"] >= 1
    assert _trees(forced.model_to_string()) == \
        _trees(clean.model_to_string())


def test_overflow_flag_roundtrip():
    health.reset_overflow()
    health.record_hist_overflow(False)
    assert not health.consume_overflow_flag()
    health.record_hist_overflow(True)
    health.record_hist_overflow(True)
    assert health.overflow_total() == 2
    assert health.consume_overflow_flag()
    assert not health.consume_overflow_flag()   # read-and-clear


# ------------------------------------------------------------- serve guards
def _serve_nan_check(params, X, y, rounds=5):
    bst = _train(params, X, y, rounds=rounds)
    pred = bst.serving_predictor()
    want = pred.predict(X[:6])
    orig = pred._predict_device

    def nan_device(Xq, sparse):
        out = np.array(orig(Xq, sparse), np.float64, copy=True)
        out[...] = np.nan
        return out

    pred._predict_device = nan_device
    got = pred.predict(X[:6])
    pred._predict_device = orig
    assert np.isfinite(got).all()
    assert pred.metrics.nan_scores == 1
    assert pred.metrics.host_fallbacks == 1
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-9)
    snap = pred.metrics_snapshot()
    assert snap["nan_scores"] == 1


def test_serve_nan_scores_host_fallback_binary():
    X, y = _data()
    _serve_nan_check(BASE, X, y)


def test_serve_nan_scores_host_fallback_multiclass():
    rng = np.random.RandomState(1)
    X = rng.rand(300, 6)
    y = (X[:, 0] * 3).astype(np.int64).clip(0, 2).astype(np.float64)
    _serve_nan_check({"objective": "multiclass", "num_class": 3,
                      "num_leaves": 7, "verbosity": -1,
                      "min_data_in_leaf": 5}, X, y, rounds=3)


def test_serve_nan_guard_respects_host_fallback_off():
    X, y = _data()
    bst = _train(BASE, X, y, rounds=3)
    pred = bst.serving_predictor()
    pred._host_fallback = False
    pred._predict_device = lambda Xq, sparse: np.full(
        (np.asarray(Xq).shape[0],), np.nan)
    out = pred.predict(X[:4])
    assert not np.isfinite(out).any()       # surfaced, not healed
    assert pred.metrics.nan_scores == 1     # but still counted


def test_serve_rejects_inf_rows():
    X, y = _data()
    bst = _train(BASE, X, y, rounds=3)
    pred = bst.serving_predictor()
    bad = X[:4].copy()
    bad[2, 1] = np.inf
    with pytest.raises(ValueError, match="inf"):
        pred.predict(bad)
    assert pred.metrics.host_fallbacks == 0   # caller error, no fallback
    batcher = pred.batcher(max_batch=8, max_wait_ms=1.0)
    try:
        with pytest.raises(ValueError, match="inf"):
            batcher.submit(bad)
        ok = batcher.submit(X[:2])            # queue still alive
        np.testing.assert_allclose(ok.result(timeout=30),
                                   pred.predict(X[:2]))
    finally:
        batcher.close()


# ------------------------------------------------------ ingestion validation
def test_nonfinite_label_rejected():
    X, y = _data(100, 4)
    y = y.copy()
    y[7] = np.nan
    with pytest.raises(ValueError, match="non-finite label"):
        _train(BASE, X, y, rounds=1)


def test_nonfinite_weight_rejected():
    X, y = _data(100, 4)
    w = np.ones(100)
    w[3] = np.inf
    with pytest.raises(ValueError, match="non-finite sample weight"):
        lgb.train(dict(BASE), lgb.Dataset(X, label=y, weight=w), 1)


def test_binary_label_domain_rejected():
    X, y = _data(100, 4)
    with pytest.raises(ValueError, match="labels in \\{0, 1\\}"):
        _train(BASE, X, y * 2.0, rounds=1)


def test_poisson_label_domain_rejected():
    X, _ = _data(100, 4)
    y = np.linspace(-1, 5, 100)
    with pytest.raises(ValueError, match="poisson requires labels >= 0"):
        lgb.train({"objective": "poisson", "verbosity": -1,
                   "min_data_in_leaf": 5}, lgb.Dataset(X, label=y), 1)


def test_gamma_label_domain_rejected():
    X, _ = _data(100, 4)
    y = np.zeros(100)
    with pytest.raises(ValueError, match="gamma requires labels > 0"):
        lgb.train({"objective": "gamma", "verbosity": -1,
                   "min_data_in_leaf": 5}, lgb.Dataset(X, label=y), 1)


def test_degenerate_column_warnings(capsys):
    rng = np.random.RandomState(0)
    X = rng.rand(200, 4)
    X[:, 1] = np.nan        # all-NaN column
    X[:, 2] = 7.25          # constant column
    y = (X[:, 0] > 0.5).astype(np.float64)
    _train(BASE, X, y, rounds=1)
    err = capsys.readouterr().err
    assert "entirely NaN" in err
    assert "constant" in err


# ------------------------------------------------------------------ tooling
def test_health_report_tool(tmp_path):
    """tools/health_report.py folds a checkpoint dir + BENCH health blocks
    into one triage table (subprocess — the CLI surface is the contract)."""
    d = str(tmp_path / "ck")
    X, y = _data()
    _train(dict(BASE, tpu_iter_pack=4, checkpoint_interval=4,
                checkpoint_dir=d, checkpoint_keep=3), X, y, rounds=8)
    bench_json = tmp_path / "BENCH_fake.json"
    bench_json.write_text(json.dumps({
        "metric": "m", "value": 1.0,
        "detail": {"health": {"policy": "warn", "verdict": "healthy",
                              "rounds_checked": 8, "rollbacks": 0,
                              "overflow_escalations": 0,
                              "last_health": {"grad_nonfinite": 0.0}},
                   "goss": {"health": {"verdict": "tripped",
                                       "rounds_checked": 3,
                                       "rollbacks": 1,
                                       "overflow_escalations": 2,
                                       "last_health": {
                                           "grad_nonfinite": 4.0}}}},
    }) + "\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "health_report.py"),
         "--ckpt", d, str(bench_json)],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "checkpoints under" in out.stdout
    assert "valid" in out.stdout
    assert "BENCH health blocks" in out.stdout
    assert "tripped" in out.stdout and "healthy" in out.stdout
    assert "4 nonfinite" in out.stdout


def test_bench_health_block_schema():
    """bench.py's post-hoc audit returns the detail.health schema with a
    real verdict over the final gradients/scores."""
    X, y = _data(200, 5)
    bst = _train(BASE, X, y, rounds=3)
    block = health.bench_health_block(bst, 3)
    assert block["verdict"] == "healthy"
    assert block["rounds_checked"] == 3
    assert block["last_health"]["grad_nonfinite"] == 0.0
    assert "overflow_escalations" in block
