"""Metric tests against sklearn / closed-form oracles."""

import numpy as np
import pytest
from sklearn import metrics as skm

from lightgbm_tpu.config import Config
from lightgbm_tpu.metrics import create_metric


def _metric(name, params=None):
    return create_metric(name, Config(params or {"objective": "regression"}))[0]


def test_l2_rmse_l1(rng):
    y = rng.randn(100)
    p = y + 0.1 * rng.randn(100)
    assert _metric("l2")(y, p) == pytest.approx(np.mean((y - p) ** 2))
    assert _metric("rmse")(y, p) == pytest.approx(
        np.sqrt(np.mean((y - p) ** 2)))
    assert _metric("l1")(y, p) == pytest.approx(np.mean(np.abs(y - p)))


def test_auc_matches_sklearn(rng):
    y = (rng.rand(500) > 0.5).astype(float)
    s = rng.randn(500) + y
    assert _metric("auc")(y, s) == pytest.approx(skm.roc_auc_score(y, s),
                                                 abs=1e-9)


def test_auc_with_ties():
    y = np.array([0, 1, 0, 1, 1, 0])
    s = np.array([0.5, 0.5, 0.2, 0.8, 0.5, 0.1])
    assert _metric("auc")(y, s) == pytest.approx(skm.roc_auc_score(y, s))


def test_weighted_auc(rng):
    y = (rng.rand(200) > 0.5).astype(float)
    s = rng.randn(200) + 0.5 * y
    w = rng.rand(200) + 0.5
    assert _metric("auc")(y, s, w) == pytest.approx(
        skm.roc_auc_score(y, s, sample_weight=w), abs=1e-9)


def test_binary_logloss(rng):
    y = (rng.rand(300) > 0.5).astype(float)
    raw = rng.randn(300)
    p = 1 / (1 + np.exp(-raw))
    assert _metric("binary_logloss")(y, raw) == pytest.approx(
        skm.log_loss(y, p), rel=1e-6)


def test_binary_error():
    y = np.array([0, 0, 1, 1])
    raw = np.array([-1.0, 1.0, 1.0, -1.0])
    assert _metric("binary_error")(y, raw) == pytest.approx(0.5)


def test_multi_logloss(rng):
    n, k = 200, 3
    y = rng.randint(0, k, n)
    raw = rng.randn(n, k)
    e = np.exp(raw - raw.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    m = create_metric("multi_logloss",
                      Config({"objective": "multiclass", "num_class": 3}))[0]
    assert m(y, raw) == pytest.approx(
        skm.log_loss(y, p, labels=list(range(k))), rel=1e-6)


def test_ndcg(rng):
    # two queries with known ordering quality
    group = np.array([5, 5])
    y = np.array([3, 2, 1, 0, 0,   0, 1, 2, 3, 0])
    perfect = np.array([5, 4, 3, 2, 1,   1, 2, 3, 4, 0], dtype=float)
    cfg = Config({"objective": "lambdarank", "eval_at": [3]})
    m = create_metric("ndcg", cfg)[0]
    assert m.name == "ndcg@3"
    assert m(y, perfect, None, group) == pytest.approx(1.0)
    worst = -perfect
    assert m(y, worst, None, group) < 0.6


def test_map(rng):
    group = np.array([4])
    y = np.array([1, 0, 1, 0])
    s = np.array([4.0, 3.0, 2.0, 1.0])
    cfg = Config({"objective": "lambdarank", "eval_at": [4]})
    m = create_metric("map", cfg)[0]
    # AP = (1/1 + 2/3) / 2
    assert m(y, s, None, group) == pytest.approx((1.0 + 2.0 / 3.0) / 2.0)


def test_average_precision_matches_sklearn(rng):
    y = (rng.rand(300) > 0.7).astype(float)
    s = rng.randn(300) + y
    assert _metric("average_precision")(y, s) == pytest.approx(
        skm.average_precision_score(y, s), abs=1e-9)


def test_auc_mu_matches_pairwise_auc():
    """auc_mu default weights reduce each pair to AUC on score_i - score_j
    (reference AucMuMetric, multiclass_metric.hpp:183)."""
    from lightgbm_tpu.metrics import _auc, _auc_mu

    rng = np.random.RandomState(0)
    n, k = 600, 3
    y = rng.randint(0, k, n).astype(np.float64)
    s = rng.randn(n, k) + 1.2 * np.eye(k)[y.astype(int)]
    got = _auc_mu(k)(y, s, None, None)
    expect = []
    for i in range(k):
        for j in range(i + 1, k):
            m = (y == i) | (y == j)
            # default W: v = e_j-ish rows -> t1*(score.v) = 2*(s_i - s_j)
            d = s[m, i] - s[m, j]
            expect.append(_auc((y[m] == i).astype(np.float64), d, None, None))
    assert abs(got - float(np.mean(expect))) < 1e-12
    assert 0.5 < got <= 1.0


def test_auc_mu_trains_as_metric():
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(1)
    X = rng.randn(400, 5)
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "metric": "auc_mu", "num_leaves": 7, "verbosity": -1},
                    lgb.Dataset(X, label=y), 3,
                    valid_sets=[lgb.Dataset(X, label=y)])
    res = bst._gbdt.eval_set()
    names = [m for _, m, _, _ in res]
    assert "auc_mu" in names
    val = dict((m, v) for _, m, v, _ in res)["auc_mu"]
    assert 0.5 < val <= 1.0
