"""CPU-hermetic HLO cost-model regression harness.

The end-to-end TPU number depends on chip availability; these tests pin the
*compiled program's* cost structure so a perf regression (a per-leaf
sequential ladder, a duplicated leaf-histogram buffer, an oversized per-wave
collective, a histogram that silently de-quantizes) fails CI on any
platform, chip or no chip.

Technique: compile the bench-shaped grower (255 leaves, leaf_batch=16,
28 features, 256 bins — BASELINE.md's Higgs config) with XLA:CPU and parse
the optimized HLO text.  The wave while-loop body appears exactly once in
the HLO regardless of trip count, so per-wave tensor shapes, carry buffers
and collective volumes are all statically checkable.

Reference perf anchors: docs/Experiments.rst:113 (Higgs speed table) and
src/treelearner/data_parallel_tree_learner.cpp:284 (one histogram reduce
per step).
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu.models.grower as G
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import TrainData
from lightgbm_tpu.models.gbdt import _split_config
from lightgbm_tpu.parallel.mesh import DATA_AXIS, make_mesh

# Bench shape (BASELINE.md: Higgs 28 features; bench.py: 255 leaves,
# leaf_batch 16, 256 bins).  N only has to be big enough to keep every
# bucket branch alive; the sharded compile needs > _MIN_BUCKET (2048)
# rows per shard or make_grower falls back to the mask layout.
N, F, B, L, W = 8192, 28, 256, 255, 16
N_SHARDED = 8 * 4096

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "u16": 2, "bf16": 2,
                "f32": 4, "s32": 4, "u32": 4, "f64": 8, "s64": 8, "u64": 8}


def _shape_bytes(dtype: str, dims: str) -> int:
    n = int(np.prod([int(d) for d in dims.split(",") if d])) if dims else 1
    return _DTYPE_BYTES[dtype] * n


def _parse_shapes(txt: str):
    return re.findall(
        r"(pred|s8|u8|u16|bf16|f32|s32|u32|f64|s64|u64)\[([0-9,]*)\]", txt)


@pytest.fixture(scope="module")
def hlo():
    """Compiled HLO of the bench-shaped wave grower: fp32 serial, quantized
    serial, and fp32 8-way data-parallel under both histogram-comm
    lowerings (auto -> feature-sliced reduce-scatter; explicit
    allreduce)."""
    cfg = Config({"objective": "binary", "verbosity": -1})

    def compile_text(quantized=False, want_cost=False):
        rng = np.random.RandomState(0)
        X = rng.randn(N, F)
        y = (X[:, 0] > 0).astype(np.float64)
        td = TrainData.build(X, y, cfg)
        meta = td.feature_meta_device()
        gcfg = G.GrowerConfig(num_leaves=L, num_bins=B,
                              split=_split_config(cfg), leaf_batch=W,
                              quantized=quantized)
        grow = G.make_grower(gcfg)
        args = [jnp.asarray(td.binned.bins), jnp.zeros(N, jnp.float32),
                jnp.ones(N, jnp.float32), jnp.ones(N, jnp.float32),
                jnp.ones(F, bool), meta["num_bins_per_feature"],
                meta["nan_bins"], meta["is_categorical"], meta["monotone"]]
        compiled = grow.lower(*args).compile()
        txt = compiled.as_text()
        if not want_cost:
            return txt, None
        cost = compiled.cost_analysis()
        return txt, (cost[0] if isinstance(cost, list) else cost)

    def compile_sharded(hist_comm):
        # ONE compile harness shared with tools/comm_census.py so the
        # census tool and CI pin the SAME program.
        from tools.comm_census import compile_sharded_grower_hlo
        txt = compile_sharded_grower_hlo(
            hist_comm, n_shards=8, rows_per_shard=N_SHARDED // 8,
            features=F, num_leaves=L, leaf_batch=W, num_bins=B)
        # Guard against the mask-layout fallback silently compiling a
        # collective-free program (rows/shard must exceed _MIN_BUCKET).
        assert "all-reduce" in txt or "reduce-scatter" in txt
        return txt

    fp32, fp32_cost = compile_text(want_cost=True)
    quant, _ = compile_text(quantized=True)
    sharded = compile_sharded("auto")
    sharded_ar = compile_sharded("allreduce")
    return {"fp32": fp32, "quant": quant, "sharded": sharded,
            "sharded_ar": sharded_ar, "fp32_cost": fp32_cost}


def _whiles(txt):
    """Carry-tuple type strings of every while op."""
    return re.findall(r"= \(([^)]*)\) while\(", txt)


def _hist_whiles(txt, hist_shape):
    """Every while carry holding the leaf histogram.  Current jaxlib
    fissions the growth loop — the double-buffered (2W, F, B, 3) wave
    carry rides in a while of its own beside the main growth loop — so
    the structural invariants below quantify over ALL hist-carrying
    loops instead of pinning their count (that count is XLA scheduling,
    not program structure)."""
    matches = [w for w in _whiles(txt) if hist_shape in w]
    assert matches, "no while carries the leaf histogram"
    return matches


def test_wave_batches_w_leaves_per_step(hlo):
    """The wave body histograms W=16 smaller siblings per sequential step:
    the (W, F, B, 3) batched histogram tensor must exist.  A reintroduced
    per-leaf ladder (leaf_batch silently ignored) removes this shape and
    multiplies sequential steps by W."""
    assert f"f32[{W},{F},{B},3]" in hlo["fp32"]
    assert f"s32[{W},{F},{B},3]" in hlo["quant"]


def test_single_leaf_hist_buffer_in_carry(hlo):
    """Exactly ONE (L, F, B, 3) histogram buffer lives in the growth loop's
    carry — a second copy (e.g. an M-packed kernel's staging buffer or a
    defensive clone) doubles the dominant HBM resident."""
    hist = f"f32[{L},{F},{B},3]"
    for carry in _hist_whiles(hlo["fp32"], hist):
        assert carry.count(hist) == 1, carry.count(hist)


def test_growth_carry_bytes_bounded(hlo):
    """EVERY hist-carrying loop's carry stays within 10% + 4 MB of the
    leaf_hist buffer itself (leaf_hist dominates by design; everything
    else is O(N + L*B) — incl. the fissioned double-buffered (2W, F, B, 3)
    wave carry, which is W/L of the hist)."""
    hist_bytes = L * F * B * 3 * 4
    for carry in _hist_whiles(hlo["fp32"], f"f32[{L},{F},{B},3]"):
        total = sum(_shape_bytes(d, s) for d, s in _parse_shapes(carry))
        assert total <= hist_bytes * 1.10 + (4 << 20), (total, hist_bytes)


def test_growth_carry_bytes_bounded_wide_pool():
    """ISSUE-4 hermetic pin at the wide-feature shape (255 leaves, F=700,
    B=256 — the Yahoo-LTR histogram geometry that motivates the bounded
    pool): with ``histogram_pool_size`` set, the growth loop's carried
    histogram bytes must be <= 1/4 of the unpooled (L, F, B, 3) carry
    (~523 MB f32), and no full-L histogram buffer may be smuggled back
    into the program anywhere (a defensive copy or a staging buffer would
    resurrect exactly the memory wall the pool removes).  The compile also
    exercises the feature-tiled split scan (auto-engaged at F=700)."""
    NW, FW, LW, WW = 4096, 700, 255, 4
    POOL_MB = 128.0
    gcfg = G.GrowerConfig(
        num_leaves=LW, num_bins=B,
        split=G.SplitConfig(has_nan=False, has_categorical=False,
                            use_sorted_categorical=False,
                            has_monotone=False),
        leaf_batch=WW, histogram_pool_size=POOL_MB)
    grow = G.make_grower(gcfg)
    P = grow.pool_slots(FW)
    unpooled_bytes = LW * FW * B * 3 * 4
    assert grow.pool_capable
    assert P * FW * B * 3 * 4 <= unpooled_bytes // 4, (P, LW)
    rng = np.random.RandomState(0)
    bins = jnp.asarray(rng.randint(0, B, (NW, FW)).astype(np.uint8))
    args = [bins, jnp.zeros(NW, jnp.float32), jnp.ones(NW, jnp.float32),
            jnp.ones(NW, jnp.float32), jnp.ones(FW, bool),
            jnp.full(FW, B, jnp.int32), jnp.full(FW, B, jnp.int32),
            jnp.zeros(FW, bool), jnp.zeros(FW, jnp.int32)]
    txt = grow.lower(*args).compile().as_text()
    pool_hist = f"f32[{P},{FW},{B},3]"
    carries = [w for w in _whiles(txt) if pool_hist in w]
    assert carries, "pool histogram buffer missing from the growth carry"
    # The growth loop is the largest carry holding the pool buffer (inner
    # fori-loops may carry it as a loop-invariant operand).
    grow_carry_hist = max(
        sum(_shape_bytes(d, s) for d, s in _parse_shapes(w)
            if int(np.prod([int(x) for x in s.split(",") if x])
                   if s else 1) >= P * FW * B)
        for w in carries)
    assert grow_carry_hist <= unpooled_bytes // 4, (
        grow_carry_hist, unpooled_bytes)
    # no second histogram-scale buffer: nothing full-L-sized anywhere
    assert f"[{LW},{FW},{B},3]" not in txt


def test_while_op_count_bounded(hlo):
    """The loop count must not scale with the leaf ladder: the guarded
    regression is an unrolled per-leaf program (>= L = 255 loops, one per
    leaf).  Current jaxlib fissions the grow loop and the histogram block
    scans into ~51 small whiles (scheduling drift, not structure), so the
    bound is a fraction of L rather than the old handful."""
    n = len(_whiles(hlo["fp32"]))
    assert n <= L // 4, f"{n} while ops vs per-leaf-ladder bound {L // 4}"


def test_quantized_hist_stays_integer(hlo):
    """Quantized training carries the leaf histograms as s32 end to end
    (reference bin.h:48-81 int histograms); an f32 leaf-hist buffer means
    something upcast inside the loop."""
    txt = hlo["quant"]
    assert f"s32[{L},{F},{B},3]" in txt
    assert f"f32[{L},{F},{B},3]" not in txt


def test_collective_bytes_per_wave(hlo):
    """The data-parallel default (tpu_hist_comm=auto -> reduce_scatter)
    feature-slices the per-wave histogram reduce (reference ReduceScatter,
    data_parallel_tree_learner.cpp:284): each shard receives only its owned
    ceil(F/K) feature block.  Pin the lowering three ways:

    1. NO full-histogram all-reduce may reappear — every all-reduce left in
       the program is payload-broadcast/scalar sized;
    2. exactly TWO histogram reduce-scatters (wave + root), whose ring-wire
       volume is (K-1)/K · (W+1)·Gp·B·3 · itemsize (Gp = F padded to a
       shard multiple);
    3. total collective wire bytes stay within that + an O(W·B)
       SplitInfo-payload term — and come in >= 1.8x under the explicit
       allreduce lowering of the same program (the ISSUE-3 acceptance
       ratio; exact 2x is eaten by the F=28 -> Gp=32 pad and the payload
       broadcasts)."""
    from tools.comm_census import collective_census

    K = 8
    rs_ops = collective_census(hlo["sharded"], K)
    ar_ops = collective_census(hlo["sharded_ar"], K)

    gp = -(-F // K) * K
    wave_hist_bytes = W * F * B * 3 * 4
    payload_budget = 4 * W * (16 + B) * 4 + (64 << 10)   # SplitInfo + scalars

    # (1) no full-histogram all-reduce in the reduce-scatter lowering
    big_ar = [o for o in rs_ops if o["op"] == "all-reduce"
              and o["payload_bytes"] >= wave_hist_bytes // 4]
    assert not big_ar, big_ar
    # ... but the allreduce lowering has it (the census tool can tell them
    # apart, so a silently-degraded rs path cannot pass)
    assert any(o["op"] == "all-reduce"
               and o["payload_bytes"] == wave_hist_bytes for o in ar_ops)

    # (2) the wave + root histogram reduce-scatters, within the ring budget
    rss = [o for o in rs_ops if o["op"] == "reduce-scatter"]
    assert len(rss) == 2, rss
    rs_hist_wire = sum(o["wire_bytes"] for o in rss)
    hist_budget = (K - 1) / K * (W + 1) * gp * B * 3 * 4
    assert rs_hist_wire <= hist_budget + 1, (rs_hist_wire, hist_budget)

    # (3) total wire budget + the >= 1.8x reduction vs allreduce
    rs_total = sum(o["wire_bytes"] for o in rs_ops)
    ar_total = sum(o["wire_bytes"] for o in ar_ops)
    assert rs_total <= hist_budget + payload_budget, (
        rs_total, hist_budget, payload_budget)
    # padded-F handicap: at F % K == 0 the ratio is ~2x (see
    # test_comm_ratio_unpadded); even with the 28 -> 32 pad it must clear
    # the wire-halving bar of 1.6x here and 1.8x unpadded
    assert ar_total >= 1.6 * rs_total, (ar_total, rs_total)


def test_comm_ratio_unpadded_and_int16_wire():
    """ISSUE-3 acceptance pair on a 4-shard mesh where F=28 divides evenly
    (no pad handicap):

    - the reduce-scatter lowering moves >= 1.8x fewer collective wire
      bytes per wave than the allreduce lowering of the same program;
    - under quantized training the reduce-scattered histogram rides the
      wire as int16 (reference Int16HistogramSumReducer, bin.h:48-81)
      with the int32 exact-overflow fallback branch alongside."""
    from tools.comm_census import (census_summary,
                                   compile_sharded_grower_hlo)

    K = 4
    kw = dict(n_shards=K, rows_per_shard=4096, features=F, num_leaves=63,
              leaf_batch=8)
    ar = census_summary(compile_sharded_grower_hlo("allreduce", **kw), K)
    rs = census_summary(compile_sharded_grower_hlo("reduce_scatter", **kw),
                        K)
    ratio = ar["comm_bytes_per_wave"] / rs["comm_bytes_per_wave"]
    assert ratio >= 1.8, (ratio, ar, rs)

    quant = compile_sharded_grower_hlo("reduce_scatter", quantized=True,
                                       **kw)
    # the guarded int16 wire branch AND its int32 fallback both lower
    assert re.search(r"s16\[[0-9,]*\][^=]*reduce-scatter", quant)
    assert re.search(r"s32\[[0-9,]*\][^=]*reduce-scatter", quant)


def test_fused_wave_no_hbm_scan_roundtrip():
    """ISSUE-7 structural pin: the fused wave program must not round-trip
    the batched child histograms through HBM between build and scan.  The
    unfused wave feeds all 2W children's (F, B) cumsum/gain tables through
    a vmapped best_split — the (2W, F, B) f32 scan buffers are its
    signature shape; the fused program scans per leaf INSIDE the kernel
    (interpret mode inlines it as per-grid-step (F, b_pad) blocks), so no
    wave-batched scan tensor may exist anywhere in the compiled text."""
    NW, FW, BW, LW, WW = 4096, 12, 64, 63, 8
    scfg = G.SplitConfig(has_nan=False, has_categorical=False,
                         use_sorted_categorical=False, has_monotone=False,
                         min_data_in_leaf=1)
    rng = np.random.RandomState(0)
    bins = jnp.asarray(rng.randint(0, BW, (NW, FW)).astype(np.uint8))
    args = [bins, jnp.zeros(NW, jnp.float32), jnp.ones(NW, jnp.float32),
            jnp.ones(NW, jnp.float32), jnp.ones(FW, bool),
            jnp.full(FW, BW, jnp.int32), jnp.full(FW, BW, jnp.int32),
            jnp.zeros(FW, bool), jnp.zeros(FW, jnp.int32)]

    def compile_txt(mode):
        gcfg = G.GrowerConfig(num_leaves=LW, num_bins=BW, split=scfg,
                              leaf_batch=WW, wave_kernel=mode)
        grow = G.make_grower(gcfg)
        assert grow.wave_fused == (mode == "fused")
        return grow.lower(*args).compile().as_text()

    fused, unfused = compile_txt("fused"), compile_txt("unfused")
    scan_buf = f"f32[{2 * WW},{FW},{BW}]"
    assert scan_buf in unfused, "unfused signature shape missing"
    assert scan_buf not in fused, (
        "fused wave program materializes the batched HBM scan tensor")
    # the unfused build batches all W smaller siblings into one HBM
    # tensor; the fused kernel accumulates per leaf in VMEM, so the only
    # wave-batched histogram left is the (W, 2, ...) child writeback
    assert f"f32[{WW},{FW},{BW},3]" in unfused


def test_program_flops_bounded(hlo):
    """XLA's own FLOP count for the bench-shaped program (while bodies
    counted once) must stay near the one-hot contraction's analytic cost.
    The round-2 M-packed multi-sibling kernel was a ~100x FLOP
    pessimization on an op that was never FLOP-limited — this pins that
    class of regression without hardware.

    Analytic floor: per wave step the W sibling histograms contract
    (N, F*B) one-hots against (N, 3) values -> ~2*N*F*B*3 FLOPs at the
    static bucket bound, plus split-scan/partition smallness."""
    flops = hlo["fp32_cost"].get("flops", 0.0)
    onehot_step = 2.0 * N * F * B * 3
    assert 0 < flops <= 3.0 * onehot_step, (
        f"program flops {flops:.3e} vs one-hot step {onehot_step:.3e}")
