"""CPU-hermetic HLO cost-model regression harness.

The end-to-end TPU number depends on chip availability; these tests pin the
*compiled program's* cost structure so a perf regression (a per-leaf
sequential ladder, a duplicated leaf-histogram buffer, an oversized per-wave
collective, a histogram that silently de-quantizes) fails CI on any
platform, chip or no chip.

Technique: compile the bench-shaped grower (255 leaves, leaf_batch=16,
28 features, 256 bins — BASELINE.md's Higgs config) with XLA:CPU and parse
the optimized HLO text.  The wave while-loop body appears exactly once in
the HLO regardless of trip count, so per-wave tensor shapes, carry buffers
and collective volumes are all statically checkable.

Reference perf anchors: docs/Experiments.rst:113 (Higgs speed table) and
src/treelearner/data_parallel_tree_learner.cpp:284 (one histogram reduce
per step).
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu.models.grower as G
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import TrainData
from lightgbm_tpu.models.gbdt import _split_config
from lightgbm_tpu.parallel.mesh import DATA_AXIS, make_mesh

# Bench shape (BASELINE.md: Higgs 28 features; bench.py: 255 leaves,
# leaf_batch 16, 256 bins).  N only has to be big enough to keep every
# bucket branch alive; the sharded compile needs > _MIN_BUCKET (2048)
# rows per shard or make_grower falls back to the mask layout.
N, F, B, L, W = 8192, 28, 256, 255, 16
N_SHARDED = 8 * 4096

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "u16": 2, "bf16": 2,
                "f32": 4, "s32": 4, "u32": 4, "f64": 8, "s64": 8, "u64": 8}


def _shape_bytes(dtype: str, dims: str) -> int:
    n = int(np.prod([int(d) for d in dims.split(",") if d])) if dims else 1
    return _DTYPE_BYTES[dtype] * n


def _parse_shapes(txt: str):
    return re.findall(
        r"(pred|s8|u8|u16|bf16|f32|s32|u32|f64|s64|u64)\[([0-9,]*)\]", txt)


@pytest.fixture(scope="module")
def hlo():
    """Compiled HLO of the bench-shaped wave grower: fp32 serial, quantized
    serial, and fp32 8-way data-parallel."""
    cfg = Config({"objective": "binary", "verbosity": -1})

    def compile_text(quantized=False, mesh=None, want_cost=False):
        n = N if mesh is None else N_SHARDED
        rng = np.random.RandomState(0)
        X = rng.randn(n, F)
        y = (X[:, 0] > 0).astype(np.float64)
        td = TrainData.build(X, y, cfg)
        meta = td.feature_meta_device()
        gcfg = G.GrowerConfig(num_leaves=L, num_bins=B,
                              split=_split_config(cfg), leaf_batch=W,
                              quantized=quantized)
        grow = G.make_grower(gcfg, mesh=mesh, data_axis=DATA_AXIS)
        args = [jnp.asarray(td.binned.bins), jnp.zeros(n, jnp.float32),
                jnp.ones(n, jnp.float32), jnp.ones(n, jnp.float32),
                jnp.ones(F, bool), meta["num_bins_per_feature"],
                meta["nan_bins"], meta["is_categorical"], meta["monotone"]]
        compiled = grow.lower(*args).compile()
        txt = compiled.as_text()
        if mesh is not None:
            # Guard against the mask-layout fallback silently compiling a
            # collective-free program (rows/shard must exceed _MIN_BUCKET).
            assert "all-reduce" in txt
        if not want_cost:
            return txt, None
        cost = compiled.cost_analysis()
        return txt, (cost[0] if isinstance(cost, list) else cost)

    fp32, fp32_cost = compile_text(want_cost=True)
    quant, _ = compile_text(quantized=True)
    sharded, _ = compile_text(mesh=make_mesh(8, 1))
    return {"fp32": fp32, "quant": quant, "sharded": sharded,
            "fp32_cost": fp32_cost}


def _whiles(txt):
    """Carry-tuple type strings of every while op."""
    return re.findall(r"= \(([^)]*)\) while\(", txt)


def _grow_while(txt, hist_shape):
    """The growth loop: the while whose carry holds the leaf histogram."""
    matches = [w for w in _whiles(txt) if hist_shape in w]
    assert len(matches) == 1, f"expected one grow loop, found {len(matches)}"
    return matches[0]


def test_wave_batches_w_leaves_per_step(hlo):
    """The wave body histograms W=16 smaller siblings per sequential step:
    the (W, F, B, 3) batched histogram tensor must exist.  A reintroduced
    per-leaf ladder (leaf_batch silently ignored) removes this shape and
    multiplies sequential steps by W."""
    assert f"f32[{W},{F},{B},3]" in hlo["fp32"]
    assert f"s32[{W},{F},{B},3]" in hlo["quant"]


def test_single_leaf_hist_buffer_in_carry(hlo):
    """Exactly ONE (L, F, B, 3) histogram buffer lives in the growth loop's
    carry — a second copy (e.g. an M-packed kernel's staging buffer or a
    defensive clone) doubles the dominant HBM resident."""
    hist = f"f32[{L},{F},{B},3]"
    carry = _grow_while(hlo["fp32"], hist)
    assert carry.count(hist) == 1, carry.count(hist)


def test_growth_carry_bytes_bounded(hlo):
    """Total growth-loop carry stays within 10% + 4 MB of the leaf_hist
    buffer itself (leaf_hist dominates by design; everything else is
    O(N + L*B))."""
    hist_bytes = L * F * B * 3 * 4
    carry = _grow_while(hlo["fp32"], f"f32[{L},{F},{B},3]")
    total = sum(_shape_bytes(d, s) for d, s in _parse_shapes(carry))
    assert total <= hist_bytes * 1.10 + (4 << 20), (total, hist_bytes)


def test_while_op_count_bounded(hlo):
    """The program stays a handful of loops (grow loop + inner fori-loops
    + histogram block scans), not an unrolled per-leaf ladder."""
    assert len(_whiles(hlo["fp32"])) <= 14, len(_whiles(hlo["fp32"]))


def test_quantized_hist_stays_integer(hlo):
    """Quantized training carries the leaf histograms as s32 end to end
    (reference bin.h:48-81 int histograms); an f32 leaf-hist buffer means
    something upcast inside the loop."""
    txt = hlo["quant"]
    assert f"s32[{L},{F},{B},3]" in txt
    assert f"f32[{L},{F},{B},3]" not in txt


def test_collective_bytes_per_wave(hlo):
    """Data-parallel moves ONE (W, F, B, 3) histogram all-reduce per wave
    plus the root histogram and O(W) scalars (reference: one reduce per
    step, data_parallel_tree_learner.cpp:284).  Reducing the full
    (L, F, B, 3) leaf_hist — or reducing the wave hist twice — blows this
    budget by an order of magnitude."""
    txt = hlo["sharded"]
    total = 0
    wave_hist_reduces = 0
    for m in re.finditer(
            r"= (pred|s8|u8|u16|bf16|f32|s32|u32|f64)\[([0-9,]*)\][^=]*"
            r"all-reduce", txt):
        total += _shape_bytes(m.group(1), m.group(2))
        if m.group(2) == f"{W},{F},{B},3":
            wave_hist_reduces += 1
    wave_bytes = W * F * B * 3 * 4
    root_bytes = F * B * 3 * 4
    assert wave_hist_reduces == 1, wave_hist_reduces
    assert total <= wave_bytes + root_bytes + (256 << 10), (
        total, wave_bytes + root_bytes)


def test_program_flops_bounded(hlo):
    """XLA's own FLOP count for the bench-shaped program (while bodies
    counted once) must stay near the one-hot contraction's analytic cost.
    The round-2 M-packed multi-sibling kernel was a ~100x FLOP
    pessimization on an op that was never FLOP-limited — this pins that
    class of regression without hardware.

    Analytic floor: per wave step the W sibling histograms contract
    (N, F*B) one-hots against (N, 3) values -> ~2*N*F*B*3 FLOPs at the
    static bucket bound, plus split-scan/partition smallness."""
    flops = hlo["fp32_cost"].get("flops", 0.0)
    onehot_step = 2.0 * N * F * B * 3
    assert 0 < flops <= 3.0 * onehot_step, (
        f"program flops {flops:.3e} vs one-hot step {onehot_step:.3e}")
