"""Histogram + split-finding op tests against numpy oracles."""

import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu.ops.histogram import (build_histogram, histogram_onehot,
                                        histogram_segment, pack_values)
from lightgbm_tpu.ops.split import SplitConfig, best_split


def _np_histogram(bins, g, h, mask, B):
    n, f = bins.shape
    out = np.zeros((f, B, 3))
    for j in range(f):
        for r in range(n):
            if mask is None or mask[r]:
                b = bins[r, j]
                out[j, b, 0] += g[r]
                out[j, b, 1] += h[r]
                out[j, b, 2] += 1.0
    return out


@pytest.mark.parametrize("impl", ["onehot", "segment"])
def test_histogram_matches_oracle(rng, impl):
    n, f, B = 500, 4, 16
    bins = rng.randint(0, B, size=(n, f)).astype(np.uint8)
    g = rng.randn(n).astype(np.float32)
    h = rng.rand(n).astype(np.float32)
    mask = (rng.rand(n) > 0.3)
    hist = build_histogram(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                           jnp.asarray(mask), num_bins=B, impl=impl,
                           rows_block=128)
    oracle = _np_histogram(bins, g, h, mask, B)
    np.testing.assert_allclose(np.asarray(hist), oracle, rtol=1e-4, atol=1e-4)


def test_histogram_impls_agree(rng):
    n, f, B = 1000, 6, 64
    bins = rng.randint(0, B, size=(n, f)).astype(np.uint8)
    vals = pack_values(jnp.asarray(rng.randn(n), dtype=jnp.float32),
                       jnp.asarray(rng.rand(n), dtype=jnp.float32), None)
    h1 = histogram_onehot(jnp.asarray(bins), vals, num_bins=B, rows_block=256)
    h2 = histogram_segment(jnp.asarray(bins), vals, num_bins=B)
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h2),
                               rtol=1e-4, atol=1e-4)


def _oracle_best_numerical(hist, pg, ph, pc, nbpf, nan_bin, cfg):
    """Brute-force split search for one numerical feature."""
    best = (-np.inf, -1, False)
    B = hist.shape[0]
    nv = nbpf - (1 if nan_bin < B else 0)
    Gn = hist[nan_bin, 0] if nan_bin < B else 0.0
    Hn = hist[nan_bin, 1] if nan_bin < B else 0.0
    Cn = hist[nan_bin, 2] if nan_bin < B else 0.0

    def lg(g, h):
        t = np.sign(g) * max(abs(g) - cfg.lambda_l1, 0)
        return t * t / (h + cfg.lambda_l2 + 1e-15)

    for t in range(nv):
        GL = hist[: t + 1, 0].sum()
        HL = hist[: t + 1, 1].sum()
        CL = hist[: t + 1, 2].sum()
        if nan_bin <= t:  # nan bin inside: skip (oracle counts value bins only)
            GL -= Gn; HL -= Hn; CL -= Cn
        for dl in ([False, True] if nan_bin < B else [False]):
            gl, hl, cl = (GL + Gn, HL + Hn, CL + Cn) if dl else (GL, HL, CL)
            gr, hr, cr = pg - gl, ph - hl, pc - cl
            if cl < max(cfg.min_data_in_leaf, 1) or cr < max(cfg.min_data_in_leaf, 1):
                continue
            if hl < cfg.min_sum_hessian_in_leaf or hr < cfg.min_sum_hessian_in_leaf:
                continue
            gain = lg(gl, hl) + lg(gr, hr) - lg(pg, ph)
            if gain > cfg.min_gain_to_split + 1e-15 and gain > best[0]:
                best = (gain, t, dl)
    return best


@pytest.mark.parametrize("with_nan", [False, True])
@pytest.mark.parametrize("l1,l2,mindata", [(0.0, 0.0, 1), (0.5, 1.0, 10)])
def test_best_split_matches_bruteforce(rng, with_nan, l1, l2, mindata):
    B, F = 16, 3
    cfg = SplitConfig(lambda_l1=l1, lambda_l2=l2, min_data_in_leaf=mindata,
                      min_sum_hessian_in_leaf=1e-3)
    hist = np.zeros((F, B, 3), np.float32)
    nbpf = np.array([16, 10, 8], np.int32)
    nan_bins = (np.array([15, 9, 16], np.int32) if with_nan
                else np.array([16, 16, 16], np.int32))
    for f in range(F):
        nb = nbpf[f]
        hist[f, :nb, 0] = rng.randn(nb) * 3
        hist[f, :nb, 1] = rng.rand(nb) + 0.1
        hist[f, :nb, 2] = rng.randint(1, 30, nb)
    # totals must agree across features (all features see the same rows):
    # rescale counts/hessians/grads so each feature sums to the same totals.
    tot = hist[0, :, :].sum(axis=0)
    for f in range(1, F):
        cur = hist[f, :, :].sum(axis=0)
        hist[f] *= (tot / cur)[None, :]
    pg, ph, pc = tot
    bs = best_split(
        jnp.asarray(hist), jnp.asarray(pg), jnp.asarray(ph), jnp.asarray(pc),
        num_bins_per_feature=jnp.asarray(nbpf),
        nan_bins=jnp.asarray(nan_bins),
        is_categorical=jnp.zeros(F, bool),
        monotone=jnp.zeros(F, jnp.int32),
        feature_mask=jnp.ones(F, bool),
        cfg=cfg,
    )
    oracle_best = (-np.inf, -1, -1, False)
    for f in range(F):
        g, t, dl = _oracle_best_numerical(
            hist[f].astype(np.float64), pg, ph, pc, nbpf[f],
            int(nan_bins[f]) if nan_bins[f] < B else B, cfg)
        if g > oracle_best[0]:
            oracle_best = (g, f, t, dl)
    got_gain = float(bs.gain)
    if oracle_best[0] == -np.inf:
        assert got_gain == -np.inf
    else:
        assert got_gain == pytest.approx(oracle_best[0], rel=1e-3)
        assert int(bs.feature) == oracle_best[1]


def test_split_respects_feature_mask(rng):
    B, F = 8, 4
    cfg = SplitConfig(min_data_in_leaf=1)
    hist = np.abs(rng.randn(F, B, 3)).astype(np.float32) + 0.1
    tot = hist[0].sum(axis=0)
    for f in range(1, F):
        hist[f] *= (tot / hist[f].sum(axis=0))[None, :]
    mask = np.array([False, True, False, False])
    bs = best_split(
        jnp.asarray(hist), *(jnp.asarray(v) for v in tot),
        num_bins_per_feature=jnp.full(F, B, jnp.int32),
        nan_bins=jnp.full(F, B, jnp.int32),
        is_categorical=jnp.zeros(F, bool),
        monotone=jnp.zeros(F, jnp.int32),
        feature_mask=jnp.asarray(mask),
        cfg=cfg,
    )
    if float(bs.gain) > -np.inf:
        assert int(bs.feature) == 1


def test_min_data_in_leaf_blocks_small_splits(rng):
    B, F = 8, 1
    hist = np.zeros((F, B, 3), np.float32)
    hist[0, :, 0] = rng.randn(B)
    hist[0, :, 1] = 1.0
    hist[0, :, 2] = 5.0  # 40 rows total, 5 per bin
    tot = hist[0].sum(axis=0)
    bs = best_split(
        jnp.asarray(hist), *(jnp.asarray(v) for v in tot),
        num_bins_per_feature=jnp.full(F, B, jnp.int32),
        nan_bins=jnp.full(F, B, jnp.int32),
        is_categorical=jnp.zeros(F, bool),
        monotone=jnp.zeros(F, jnp.int32),
        feature_mask=jnp.ones(F, bool),
        cfg=SplitConfig(min_data_in_leaf=100),
    )
    assert float(bs.gain) == -np.inf


def test_pallas_histogram_matches_segment(rng):
    """Pallas kernel (interpret mode on CPU) vs scatter oracle."""
    from lightgbm_tpu.ops.pallas_histogram import histogram_pallas

    n, f, B = 700, 5, 32
    bins = rng.randint(0, B, size=(n, f)).astype(np.uint8)
    vals = pack_values(jnp.asarray(rng.randn(n), dtype=jnp.float32),
                       jnp.asarray(rng.rand(n), dtype=jnp.float32),
                       jnp.asarray(rng.rand(n) > 0.5))
    got = histogram_pallas(jnp.asarray(bins), vals, num_bins=B,
                           rows_block=256, interpret=True)
    ref = histogram_segment(jnp.asarray(bins), vals, num_bins=B)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_flat_histogram_dtypes_match_oracle(rng):
    """Flat-matmul kernel (f32 / bf16 / int8) vs scatter oracle."""
    from lightgbm_tpu.ops.pallas_histogram import histogram_flat

    n, f, B = 700, 5, 32
    bins = rng.randint(0, B, size=(n, f)).astype(np.uint8)
    vals = pack_values(jnp.asarray(rng.randn(n), dtype=jnp.float32),
                       jnp.asarray(rng.rand(n), dtype=jnp.float32),
                       jnp.asarray(rng.rand(n) > 0.5))
    ref = np.asarray(histogram_segment(jnp.asarray(bins), vals, num_bins=B))
    got = histogram_flat(jnp.asarray(bins), vals, num_bins=B,
                         rows_block=256, dtype="f32", interpret=True)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-4, atol=1e-4)
    got16 = histogram_flat(jnp.asarray(bins), vals, num_bins=B,
                           rows_block=256, dtype="bf16", interpret=True)
    np.testing.assert_allclose(np.asarray(got16), ref, rtol=2e-2, atol=2e-1)

    vals8 = jnp.asarray(rng.randint(-16, 16, size=(n, 3)), jnp.int8)
    got8 = histogram_flat(jnp.asarray(bins), vals8, num_bins=B,
                          rows_block=256, dtype="int8", interpret=True)
    ref8 = np.zeros((f, B, 3), np.int64)
    v8 = np.asarray(vals8, np.int64)
    for j in range(f):
        for r in range(n):
            ref8[j, bins[r, j]] += v8[r]
    assert got8.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(got8, np.int64), ref8)




def test_flat_histogram_bench_bin_count(rng):
    """max_bin=255 regression: 255 bins made the kernel's one-hot flatten a
    Mosaic-illegal shape cast on hardware (merged minor dim 7140 is not
    128-aligned); the kernel now pads the bin axis to a 128-multiple and
    phantom bins must stay exactly zero."""
    from lightgbm_tpu.ops.pallas_histogram import histogram_flat

    n, f, B = 768, 28, 255
    bins = rng.randint(0, B, size=(n, f)).astype(np.uint8)
    vals = pack_values(jnp.asarray(rng.randn(n), dtype=jnp.float32),
                       jnp.asarray(rng.rand(n), dtype=jnp.float32),
                       jnp.asarray(rng.rand(n) > 0.3))
    ref = np.asarray(histogram_segment(jnp.asarray(bins), vals, num_bins=B))
    got = histogram_flat(jnp.asarray(bins), vals, num_bins=B, interpret=True)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-4, atol=1e-4)


def test_flat_histogram_layout_mosaic_alignment():
    """Hardware-independent guard for the max_bin=255 Mosaic regression:
    interpret-mode parity cannot see layout legality, so pin the
    constraints structurally — the padded bin axis, the one-hot flatten
    width, the packed4 half-width, and the row block must all be
    128-aligned for every bin count and dtype."""
    from lightgbm_tpu.ops.pallas_histogram import kernel_layout

    for dtype in ("f32", "bf16", "int8"):
        for num_bins in (2, 15, 16, 63, 255, 256, 300):
            for f in (1, 28, 300):
                blk, ftile, cols_tile, b_pad = kernel_layout(
                    f, num_bins, dtype)
                assert b_pad % 128 == 0 and b_pad >= num_bins
                assert (ftile * b_pad) % 128 == 0
                assert blk % 128 == 0
            blk, ftile, cols_tile, b_pad = kernel_layout(
                28, num_bins, dtype, packed4=True)
            assert ftile % 2 == 0 and ftile == 2 * cols_tile
            assert ((ftile // 2) * b_pad) % 128 == 0  # nibble-plane halves
