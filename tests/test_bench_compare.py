"""tools/bench_compare.py — the bench regression gate (ISSUE-10):

- pair mode passes on identical/improved blobs, fails (rc 1) on an
  injected >= 10% regression, honors per-metric threshold overrides, and
  REFUSES (rc 3) to compare a CPU-fallback blob against a live-TPU one;
- trajectory mode walks the COMMITTED BENCH_r01..r05.json sequence:
  parses all five wrapper files, reports the wedged rounds (no salvaged
  metric line) without dying, and flags the known r02 (TPU) -> r03+ (CPU
  fallback) discontinuity as probe-mismatch rather than a regression —
  the tier-1-visible CI smoke over the real trajectory.
"""

import copy
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "bench_compare.py")

sys.path.insert(0, REPO)

from tools.bench_compare import (blob_platform, compare_pair,  # noqa: E402
                                 extract_metrics, is_cpu_fallback,
                                 load_blob)

BASE = {
    "metric": "binary_255leaves_row_iters_per_sec",
    "value": 1_000_000.0,
    "detail": {
        "platform": "tpu",
        "probe": {"verdict": "live", "backend": "tpu"},
        "train_time_s": 10.0, "iters": 20,
        "dispatches_per_iter": 1.0,
        "predict": {"warm_qps": 500.0},
        "hlo_cost": {"flops": 1e9, "bytes_accessed": 2e9},
        "memory": {"device": {"bytes_in_use": 9e5,
                              "peak_bytes_in_use": 1e6},
                   "compile": {"count": 3, "seconds": 5.0}},
    },
}


def _blob(**mods):
    b = copy.deepcopy(BASE)
    d = b["detail"]
    for key, val in mods.items():
        if key == "cpu":
            d["platform"] = "cpu"
            d["probe"]["backend"] = "cpu"
            d["cpu_fallback"] = True
        elif key in ("train_time_s", "iters", "dispatches_per_iter"):
            d[key] = val
        elif key == "qps":
            d["predict"]["warm_qps"] = val
        elif key == "peak_hbm":
            d["memory"]["device"]["peak_bytes_in_use"] = val
        elif key == "compile_s":
            d["memory"]["compile"]["seconds"] = val
        else:
            raise KeyError(key)
    return b


def _write(tmp_path, name, blob):
    path = str(tmp_path / name)
    with open(path, "w") as fh:
        json.dump(blob, fh)
    return path


def _run(*argv):
    return subprocess.run([sys.executable, TOOL, *argv],
                          capture_output=True, text=True, timeout=120)


# -------------------------------------------------------------- extraction
def test_extract_metrics_covers_watched_set():
    m = extract_metrics(BASE)
    assert m["train_s_per_iter"] == 0.5
    assert m["predict_qps"] == 500.0
    assert m["hlo_flops"] == 1e9 and m["hlo_bytes"] == 2e9
    assert m["peak_hbm_bytes"] == 1e6
    assert m["compile_s"] == 5.0
    assert m["dispatches_per_iter"] == 1.0


def test_platform_prefers_probe_block():
    b = _blob()
    b["detail"]["platform"] = "cpu"        # stale self-report
    assert blob_platform(b) == "tpu"       # probe verdict wins
    assert not is_cpu_fallback(b)
    assert is_cpu_fallback(_blob(cpu=True))


def test_load_blob_accepts_all_three_shapes(tmp_path):
    raw = _write(tmp_path, "raw.json", BASE)
    wrapper = _write(tmp_path, "wrap.json",
                     {"n": 2, "rc": 0, "tail": "...", "parsed": BASE})
    wedged = _write(tmp_path, "wedged.json",
                    {"n": 3, "rc": 1, "tail": "...", "parsed": None})
    result = _write(tmp_path, "res.json",
                    {"result": BASE, "attempts": {}})
    assert load_blob(raw)["value"] == BASE["value"]
    assert load_blob(wrapper)["value"] == BASE["value"]
    assert load_blob(wedged) is None
    assert load_blob(result)["value"] == BASE["value"]
    bad = _write(tmp_path, "bad.json", {"hello": 1})
    with pytest.raises(ValueError):
        load_blob(bad)


def test_compare_pair_missing_metrics_are_na():
    lean = {"metric": "m", "value": 1.0,
            "detail": {"train_time_s": 10.0, "iters": 20,
                       "platform": "cpu"}}
    rows, regressed = compare_pair(lean, lean, 0.10, {})
    verdicts = {r[0]: r[4] for r in rows}
    assert verdicts["train_s_per_iter"] == "ok"
    assert verdicts["predict_qps"] == "n/a"
    assert verdicts["peak_hbm_bytes"] == "n/a"
    assert not regressed


# --------------------------------------------------------------- pair CLI
def test_pair_identical_passes(tmp_path):
    a = _write(tmp_path, "a.json", _blob())
    b = _write(tmp_path, "b.json", _blob())
    r = _run(a, b)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout


def test_pair_injected_regression_fails(tmp_path):
    a = _write(tmp_path, "a.json", _blob())
    b = _write(tmp_path, "b.json", _blob(train_time_s=11.5))  # +15%
    r = _run(a, b)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "REGRESS" in r.stdout and "train_s_per_iter" in r.stdout


def test_pair_higher_better_direction(tmp_path):
    a = _write(tmp_path, "a.json", _blob())
    worse = _write(tmp_path, "b.json", _blob(qps=400.0))   # -20% QPS
    better = _write(tmp_path, "c.json", _blob(qps=600.0))
    assert _run(a, worse).returncode == 1
    r = _run(a, better)
    assert r.returncode == 0
    assert "improved" in r.stdout


def test_pair_memory_metrics_gated(tmp_path):
    a = _write(tmp_path, "a.json", _blob())
    b = _write(tmp_path, "b.json", _blob(peak_hbm=1.3e6))   # +30% HBM
    assert _run(a, b).returncode == 1
    c = _write(tmp_path, "c.json", _blob(compile_s=20.0))
    assert _run(a, c).returncode == 1
    # per-metric override loosens just that metric
    assert _run(a, c, "--metric-max", "compile_s=4.0").returncode == 0


def test_pair_threshold_flag(tmp_path):
    a = _write(tmp_path, "a.json", _blob())
    b = _write(tmp_path, "b.json", _blob(train_time_s=11.5))  # +15%
    assert _run(a, b, "--max-regress", "0.25").returncode == 0


def test_pair_probe_mismatch_refused(tmp_path):
    tpu = _write(tmp_path, "tpu.json", _blob())
    cpu = _write(tmp_path, "cpu.json", _blob(cpu=True))
    r = _run(tpu, cpu)
    assert r.returncode == 3, r.stdout + r.stderr
    assert "probe-mismatch" in r.stderr
    # same-platform CPU blobs DO compare (the PR-6 honesty block rule:
    # CPU-fallback compares only against CPU-fallback)
    cpu2 = _write(tmp_path, "cpu2.json", _blob(cpu=True))
    assert _run(cpu, cpu2).returncode == 0


def test_unreadable_input_is_usage_error(tmp_path):
    a = _write(tmp_path, "a.json", _blob())
    r = _run(a, str(tmp_path / "missing.json"))
    assert r.returncode == 2


# --------------------------------------------------- committed trajectory
def test_trajectory_over_committed_bench_rounds():
    """CI smoke (ISSUE-10 satellite): the tool walks the five committed
    BENCH_r*.json wrapper blobs, reports the wedged rounds, and flags the
    r02 (TPU) -> r03+ (CPU fallback) cliff as probe-mismatch — exit 0,
    because a backend discontinuity is not a code regression."""
    files = sorted(f for f in os.listdir(REPO)
                   if f.startswith("BENCH_r") and f.endswith(".json"))
    assert len(files) >= 5, files
    r = _run("--trajectory", REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    for name in files:
        assert name in r.stdout        # every round parsed and listed
    assert "probe-mismatch" in r.stdout
    assert "no metric blob" in r.stdout
    assert "OK" in r.stdout.splitlines()[-1]


def test_trajectory_synthetic_regression_fails(tmp_path):
    _write(tmp_path, "BENCH_r01.json",
           {"n": 1, "rc": 0, "tail": "", "parsed": _blob()})
    _write(tmp_path, "BENCH_r02.json",
           {"n": 2, "rc": 0, "tail": "", "parsed": _blob(train_time_s=13.0)})
    r = _run("--trajectory", str(tmp_path))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "REGRESSED" in r.stdout


# --------------------------------------------------- serve trajectory (I-14)
def _serve_blob(**top):
    b = {
        "metric": "BENCH_serve", "mode": "load",
        "offered_qps": 100.0, "achieved_qps": 99.0,
        "p50_ms": 2.0, "p99_ms": 9.0, "p999_ms": 14.0,
        "slo_qps": 120.0,
        "detail": {"platform": "cpu", "cpu_fallback": True},
    }
    b.update(top)
    return b


def test_serve_trajectory_committed_fixture():
    """The committed serve-trajectory smoke (ISSUE-14 satellite): two
    BENCH_serve_r*.json wrapper files walk through trajectory mode, the
    load-gate metrics (achieved QPS / p999 / slo_qps) compare, rc 0."""
    fix = os.path.join(REPO, "tests", "fixtures", "serve_traj")
    files = sorted(os.listdir(fix))
    assert files == ["BENCH_serve_r01.json", "BENCH_serve_r02.json"]
    r = _run("--trajectory", fix)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "BENCH_serve_r01.json -> BENCH_serve_r02.json" in r.stdout
    for metric in ("serve_achieved_qps", "serve_p999_ms", "serve_p99_ms"):
        assert metric in r.stdout
    assert "1 compared" in r.stdout
    assert r.stdout.splitlines()[-1].endswith("OK")


def test_serve_trajectory_families_never_cross_compare(tmp_path):
    """A directory holding BOTH families compares train rounds against
    train rounds and serve rounds against serve rounds — never across
    (every cross metric would be n/a and the pair count would lie)."""
    _write(tmp_path, "BENCH_r01.json",
           {"n": 1, "rc": 0, "tail": "", "parsed": _blob()})
    _write(tmp_path, "BENCH_r02.json",
           {"n": 2, "rc": 0, "tail": "", "parsed": _blob()})
    _write(tmp_path, "BENCH_serve_r01.json",
           {"n": 3, "rc": 0, "tail": "", "parsed": _serve_blob()})
    _write(tmp_path, "BENCH_serve_r02.json",
           {"n": 4, "rc": 0, "tail": "", "parsed": _serve_blob()})
    r = _run("--trajectory", str(tmp_path))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "BENCH_r01.json -> BENCH_r02.json" in r.stdout
    assert "BENCH_serve_r01.json -> BENCH_serve_r02.json" in r.stdout
    assert "BENCH_r02.json -> BENCH_serve_r01.json" not in r.stdout
    assert "2 compared" in r.stdout


def test_serve_trajectory_regression_and_probe_refusal(tmp_path):
    """The serve gate fails on a load-metric regression and keeps the
    probe-honesty refusal: a CPU-fallback serve blob never compares
    against a live-accelerator one."""
    _write(tmp_path, "BENCH_serve_r01.json",
           {"n": 1, "rc": 0, "tail": "", "parsed": _serve_blob()})
    _write(tmp_path, "BENCH_serve_r02.json",
           {"n": 2, "rc": 0, "tail": "",
            "parsed": _serve_blob(p999_ms=28.0, achieved_qps=60.0)})
    r = _run("--trajectory", str(tmp_path))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "serve_p999_ms" in r.stdout and "REGRESSED" in r.stdout
    # pair-mode refusal on a platform cliff (same rule as training blobs)
    tpu = _serve_blob()
    tpu["detail"] = {"platform": "tpu", "cpu_fallback": False,
                     "probe": {"verdict": "live", "backend": "tpu"}}
    a = _write(tmp_path, "serve_tpu.json", tpu)
    b = _write(tmp_path, "serve_cpu.json", _serve_blob())
    r = _run(a, b)
    assert r.returncode == 3, r.stdout + r.stderr
