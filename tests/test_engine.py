"""End-to-end training tests (modelled on the reference's
tests/python_package_test/test_engine.py strategy: synthetic sklearn data,
metric thresholds, model round-trips, param interactions)."""

import numpy as np
import pytest
from sklearn.datasets import make_classification, make_regression

import lightgbm_tpu as lgb


def _cls_data(n=3000, seed=7, **kw):
    X, y = make_classification(n_samples=n, n_features=20, n_informative=10,
                               random_state=seed, **kw)
    cut = int(n * 0.8)
    return X[:cut], y[:cut], X[cut:], y[cut:]


def test_regression_learns(rng):
    X, y = make_regression(n_samples=2000, n_features=10, noise=0.1,
                           random_state=42)
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "min_data_in_leaf": 5, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=50)
    mse = np.mean((y - bst.predict(X)) ** 2)
    assert mse < 0.05 * y.var()


def test_binary_auc_threshold():
    Xtr, ytr, Xva, yva = _cls_data()
    ds = lgb.Dataset(Xtr, label=ytr)
    ev = {}
    lgb.train({"objective": "binary", "metric": "auc", "verbosity": -1},
              ds, 50, valid_sets=[lgb.Dataset(Xva, label=yva, reference=ds)],
              callbacks=[lgb.record_evaluation(ev)])
    assert ev["valid_0"]["auc"][-1] > 0.95


def test_early_stopping_triggers():
    Xtr, ytr, Xva, yva = _cls_data(n=1500)
    ds = lgb.Dataset(Xtr, label=ytr)
    va = lgb.Dataset(Xva, label=yva, reference=ds)
    bst = lgb.train({"objective": "binary", "metric": "binary_logloss",
                     "learning_rate": 0.3, "verbosity": -1},
                    ds, 500, valid_sets=[va],
                    callbacks=[lgb.early_stopping(10, verbose=False)])
    assert bst.best_iteration > 0
    assert bst.current_iteration < 500


def test_multiclass_accuracy():
    X, y = make_classification(n_samples=3000, n_features=15, n_informative=10,
                               n_classes=4, random_state=3)
    bst = lgb.train({"objective": "multiclass", "num_class": 4,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 30)
    pred = bst.predict(X)
    assert pred.shape == (3000, 4)
    np.testing.assert_allclose(pred.sum(axis=1), 1.0, rtol=1e-5)
    assert (pred.argmax(1) == y).mean() > 0.9


@pytest.mark.parametrize("objective", [
    "regression_l1", "huber", "fair", "quantile", "mape"])
def test_robust_regression_objectives(objective):
    X, y = make_regression(n_samples=1500, n_features=8, noise=0.2,
                           random_state=0)
    # Moderate label scale: fair/huber Newton steps assume O(1) residuals
    # (their default c/alpha are O(1)); keep MAPE away from zero labels.
    y = 10.0 * y / y.std() + 100
    bst = lgb.train({"objective": objective, "alpha": 0.5,
                     "min_data_in_leaf": 5, "verbosity": -1},
                    lgb.Dataset(X, label=y), 60)
    mae = np.mean(np.abs(y - bst.predict(X)))
    assert mae < 0.5 * np.abs(y - y.mean()).mean()


@pytest.mark.parametrize("objective", ["poisson", "gamma", "tweedie"])
def test_positive_regression_objectives(objective):
    rng = np.random.RandomState(1)
    X = rng.randn(1500, 6)
    rate = np.exp(0.5 * X[:, 0] - 0.4 * X[:, 1])
    if objective == "gamma":
        y = rng.gamma(2.0, rate / 2.0) + 1e-3  # strictly positive, mean=rate
    else:
        y = rng.poisson(rate).astype(np.float64)
    bst = lgb.train({"objective": objective, "min_data_in_leaf": 5,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 40)
    pred = bst.predict(X)
    assert (pred > 0).all()
    corr = np.corrcoef(pred, rate)[0, 1]
    assert corr > 0.7


def test_bagging_and_feature_fraction():
    Xtr, ytr, Xva, yva = _cls_data()
    ds = lgb.Dataset(Xtr, label=ytr)
    ev = {}
    lgb.train({"objective": "binary", "metric": "auc",
               "bagging_fraction": 0.6, "bagging_freq": 1,
               "feature_fraction": 0.7, "verbosity": -1},
              ds, 40, valid_sets=[lgb.Dataset(Xva, label=yva, reference=ds)],
              callbacks=[lgb.record_evaluation(ev)])
    assert ev["valid_0"]["auc"][-1] > 0.93


def test_goss_sampling():
    Xtr, ytr, Xva, yva = _cls_data()
    ds = lgb.Dataset(Xtr, label=ytr)
    ev = {}
    lgb.train({"objective": "binary", "metric": "auc",
               "data_sample_strategy": "goss", "verbosity": -1},
              ds, 40, valid_sets=[lgb.Dataset(Xva, label=yva, reference=ds)],
              callbacks=[lgb.record_evaluation(ev)])
    assert ev["valid_0"]["auc"][-1] > 0.93


def test_dart_boosting():
    Xtr, ytr, Xva, yva = _cls_data(n=1500)
    ds = lgb.Dataset(Xtr, label=ytr)
    ev = {}
    lgb.train({"objective": "binary", "boosting": "dart", "metric": "auc",
               "drop_rate": 0.2, "verbosity": -1},
              ds, 40, valid_sets=[lgb.Dataset(Xva, label=yva, reference=ds)],
              callbacks=[lgb.record_evaluation(ev)])
    assert ev["valid_0"]["auc"][-1] > 0.9


def test_rf_boosting():
    Xtr, ytr, Xva, yva = _cls_data(n=1500)
    ds = lgb.Dataset(Xtr, label=ytr)
    ev = {}
    lgb.train({"objective": "binary", "boosting": "rf", "metric": "auc",
               "bagging_fraction": 0.7, "bagging_freq": 1, "verbosity": -1},
              ds, 30, valid_sets=[lgb.Dataset(Xva, label=yva, reference=ds)],
              callbacks=[lgb.record_evaluation(ev)])
    assert ev["valid_0"]["auc"][-1] > 0.9


def test_custom_objective():
    X, y = make_regression(n_samples=1000, n_features=8, noise=0.1,
                           random_state=5)
    ds = lgb.Dataset(X, label=y)
    # custom gradients cross the API boundary per iteration
    # (reference LGBM_BoosterUpdateOneIterCustom, c_api.cpp:2073)
    bst = lgb.Booster(params={"objective": "custom", "min_data_in_leaf": 5,
                              "verbosity": -1}, train_set=ds)
    for _ in range(40):
        bst.update(fobj=lambda score, ts: (score - y, np.ones_like(score)))
    mse = np.mean((y - bst.predict(X, raw_score=True)) ** 2)
    assert mse < 0.1 * y.var()


def test_callable_objective_in_params():
    X, y = make_regression(n_samples=800, n_features=6, noise=0.1,
                           random_state=8)

    def l2_obj(score, train_data):
        return score - y, np.ones_like(score)

    bst = lgb.train({"objective": l2_obj, "min_data_in_leaf": 5,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 40)
    mse = np.mean((y - bst.predict(X, raw_score=True)) ** 2)
    assert mse < 0.1 * y.var()


def test_custom_objective_without_fobj_raises():
    X, y = make_regression(n_samples=100, n_features=3, random_state=9)
    bst = lgb.Booster(params={"objective": "custom", "verbosity": -1},
                      train_set=lgb.Dataset(X, label=y))
    import pytest as _pytest
    with _pytest.raises(ValueError, match="custom"):
        bst.update()


def test_bagging_child_counts_consistent():
    """Out-of-bag rows must not leak into child histogram counts (they would
    corrupt min_data_in_leaf and histogram subtraction)."""
    rng = np.random.RandomState(17)
    X = rng.randn(1000, 4)
    y = (X[:, 0] > 0).astype(float)
    # min_data_in_leaf > bagged rows per leaf forces the count constraint to
    # actually bind; success = training still learns and never produces
    # impossible splits (which would show up as NaN/garbage predictions).
    bst = lgb.train({"objective": "binary", "bagging_fraction": 0.5,
                     "bagging_freq": 1, "min_data_in_leaf": 30,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 20)
    pred = bst.predict(X)
    assert np.isfinite(pred).all()
    assert ((pred > 0.5) == y).mean() > 0.9
    # every leaf count recorded must respect min_data_in_leaf on bagged data
    for tree in bst._gbdt.models[0]:
        if tree.num_leaves > 1:
            assert (tree.leaf_count[: tree.num_leaves] >= 30).all()


def test_missing_values_learned():
    rng = np.random.RandomState(9)
    X = rng.randn(2000, 5)
    # Signal: feature 0 missing  <=>  positive class (pure missing signal).
    y = (rng.rand(2000) < 0.5).astype(int)
    X[y == 1, 0] = np.nan
    bst = lgb.train({"objective": "binary", "verbosity": -1},
                    lgb.Dataset(X, label=y), 10)
    pred = bst.predict(X)
    assert ((pred > 0.5) == y).mean() > 0.99


def test_categorical_feature_learned():
    rng = np.random.RandomState(11)
    n = 2000
    cat = rng.randint(0, 10, n)
    X = np.column_stack([cat.astype(float), rng.randn(n)])
    y = (np.isin(cat, [2, 5, 7])).astype(int)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y, categorical_feature=[0]), 20)
    pred = bst.predict(X)
    assert ((pred > 0.5) == y).mean() > 0.99


def test_monotone_constraints():
    rng = np.random.RandomState(13)
    X = rng.rand(2000, 2)
    y = 2 * X[:, 0] + 0.3 * rng.randn(2000)
    bst = lgb.train({"objective": "regression", "monotone_constraints": [1, 0],
                     "min_data_in_leaf": 5, "verbosity": -1},
                    lgb.Dataset(X, label=y), 30)
    grid = np.linspace(0.05, 0.95, 20)
    Xg = np.column_stack([grid, np.full(20, 0.5)])
    pred = bst.predict(Xg)
    # predictions non-decreasing in the constrained feature
    assert (np.diff(pred) >= -1e-6).all()


def test_weights_affect_training():
    X, y = make_regression(n_samples=1000, n_features=5, noise=0.1,
                           random_state=2)
    w = np.ones(1000)
    w[:500] = 100.0
    bst = lgb.train({"objective": "regression", "min_data_in_leaf": 5,
                     "verbosity": -1},
                    lgb.Dataset(X, label=y, weight=w), 30)
    pred = bst.predict(X)
    mse_heavy = np.mean((y[:500] - pred[:500]) ** 2)
    mse_light = np.mean((y[500:] - pred[500:]) ** 2)
    assert mse_heavy < mse_light


def test_cv_runs():
    X, y = make_regression(n_samples=600, n_features=5, noise=0.1,
                           random_state=4)
    res = lgb.cv({"objective": "regression", "min_data_in_leaf": 5,
                  "verbosity": -1}, lgb.Dataset(X, label=y),
                 num_boost_round=10, nfold=3)
    assert "valid l2-mean" in res
    assert len(res["valid l2-mean"]) == 10
    assert res["valid l2-mean"][-1] < res["valid l2-mean"][0]


def test_feature_importance():
    rng = np.random.RandomState(21)
    X = rng.randn(1500, 5)
    y = 3 * X[:, 2] + 0.1 * rng.randn(1500)
    bst = lgb.train({"objective": "regression", "min_data_in_leaf": 5,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 20)
    imp = bst.feature_importance()
    assert imp.argmax() == 2


def test_rollback_one_iter():
    X, y = make_regression(n_samples=500, n_features=5, random_state=6)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "regression",
                              "min_data_in_leaf": 5, "verbosity": -1},
                      train_set=ds)
    for _ in range(5):
        bst.update()
    p5 = bst.predict(X)
    bst.update()
    bst.rollback_one_iter()
    p5b = bst.predict(X)
    np.testing.assert_allclose(p5, p5b, rtol=1e-5)


def test_max_depth_one_gives_stumps():
    from sklearn.datasets import make_classification

    X, y = make_classification(n_samples=500, n_features=6, random_state=0)
    bst = lgb.train({"objective": "binary", "max_depth": 1, "num_leaves": 31,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 5)
    for tree in bst._gbdt.models[0]:
        assert tree.num_leaves == 2  # stumps, not empty trees


def test_goss_other_rate_zero():
    from sklearn.datasets import make_classification

    X, y = make_classification(n_samples=500, n_features=6, random_state=0)
    bst = lgb.train({"objective": "binary", "data_sample_strategy": "goss",
                     "other_rate": 0.0, "top_rate": 0.3, "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(X, label=y), 5)
    assert bst.num_trees() >= 1


def test_goss_device_mask_semantics():
    """Device GOSS keeps exactly top_k rows at weight 1, ~other_k rows
    amplified, rest zero (reference goss.hpp:30-60)."""
    import jax
    import numpy as np
    from lightgbm_tpu.sampling import goss_mask_device

    rng = np.random.RandomState(0)
    n = 5000
    g = rng.randn(n).astype(np.float32)
    h = np.full(n, 0.25, np.float32)
    top_k, other_k = 500, 750
    amp = (1.0 - 0.1) / 0.15
    mask = np.asarray(goss_mask_device(g, h, jax.random.PRNGKey(0),
                                       top_k, other_k, amp))
    assert (mask == 1.0).sum() == top_k
    assert abs((np.isclose(mask, amp)).sum() - other_k) <= 1
    # top set really is the top |g*h|
    score = np.abs(g * h)
    thr = np.sort(score)[-top_k]
    assert score[mask == 1.0].min() >= thr - 1e-7
    assert (mask == 0.0).sum() == n - top_k - np.isclose(mask, amp).sum()


def test_degenerate_stop_deferred_exactly_one_extra():
    """The per-round deterministic fused path defers the degenerate-stop
    fetch by one iteration (pipelining): driving update() directly, a
    constant target stops exactly one iteration after the first degenerate
    tree — two stored trees, which pins that the deferral is active on the
    per-round path.  (engine.train now routes this config through the
    iteration-packed path, whose pack-boundary check stores no stumps at
    all — pinned in tests/test_iter_pack.py.)"""
    X = np.random.RandomState(0).randn(500, 4)
    y = np.zeros(500)
    bst = lgb.Booster(params={"objective": "regression", "verbosity": -1,
                              "num_leaves": 7},
                      train_set=lgb.Dataset(X, label=y))
    for _ in range(10):
        if bst.update():
            break
    assert bst.num_trees() == 2


def test_degenerate_stop_immediate_with_dart():
    """DART mutates scores between iterations, so its stop check must stay
    immediate: a constant target stops after the first degenerate tree."""
    X = np.random.RandomState(0).randn(500, 4)
    y = np.zeros(500)
    bst = lgb.train({"objective": "regression", "boosting": "dart",
                     "verbosity": -1, "num_leaves": 7},
                    lgb.Dataset(X, label=y), 10)
    assert bst.num_trees() == 1


def test_mosaic_compile_failure_degrades_to_onehot(monkeypatch):
    """A Pallas/Mosaic kernel compile failure mid-training must degrade to
    the XLA one-hot histogram (with a warning) and produce the same model,
    not crash (docs/PERF.md round 5: layout legality is invisible off-TPU)."""
    from lightgbm_tpu.ops import pallas_histogram

    def boom(*a, **k):
        raise RuntimeError(
            "Mosaic failed to compile TPU kernel: infer-vector-layout: "
            "unsupported shape cast (simulated)")

    monkeypatch.setattr(pallas_histogram, "histogram_flat", boom)
    X, y = make_regression(n_samples=600, n_features=6, noise=0.1,
                           random_state=3)
    params = {"objective": "regression", "verbosity": -1, "num_leaves": 15,
              "tpu_histogram_impl": "pallas"}
    bst = lgb.train(params, lgb.Dataset(X, label=y), 8)
    ref = lgb.train({**params, "tpu_histogram_impl": "onehot"},
                    lgb.Dataset(X, label=y), 8)
    np.testing.assert_allclose(bst.predict(X), ref.predict(X),
                               rtol=1e-6, atol=1e-6)


def test_explicit_impl_failure_raises(monkeypatch):
    """An explicit non-pallas impl choice must fail loudly, not degrade."""
    from lightgbm_tpu.ops import histogram

    def boom(*a, **k):
        raise RuntimeError("Mosaic failed to compile TPU kernel (simulated)")

    monkeypatch.setattr(histogram, "histogram_segment", boom)
    X, y = make_regression(n_samples=300, n_features=4, noise=0.1,
                           random_state=3)
    with pytest.raises(Exception, match="[Mm]osaic"):
        lgb.train({"objective": "regression", "verbosity": -1,
                   "tpu_histogram_impl": "segment"},
                  lgb.Dataset(X, label=y), 3)
