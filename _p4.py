import time, numpy as np, jax, jax.numpy as jnp
rng = np.random.RandomState(0)
n = 1000000
perm = jnp.asarray(rng.permutation(n).astype(np.int32))
vals = jnp.asarray(rng.randint(0,255,n).astype(np.int32))

def timeit(name, f, arg, reps=3):
    r = f(arg); jax.device_get(r.ravel()[0])
    t0=time.time()
    for _ in range(reps): r = f(arg); jax.device_get(r.ravel()[0])
    print(f"{name}: {(time.time()-t0)/reps*1000:.1f} ms")

timeit("scatter set 1M", jax.jit(lambda p: jnp.zeros(n,jnp.int32).at[p].set(vals)), perm)
timeit("scatter set 1M unique", jax.jit(lambda p: jnp.zeros(n,jnp.int32).at[p].set(vals, unique_indices=True, mode='promise_in_bounds')), perm)
timeit("argsort 1M", jax.jit(lambda p: jnp.argsort(p)), perm)
timeit("gather 1M", jax.jit(lambda p: vals[p]), perm)
# searchsorted-based partition at S=8192, in-loop marginal cost
S = 8192
seg = jnp.asarray(rng.randint(0,n,S).astype(np.int32))
def part_gather(c):
    gl = (seg + c.astype(jnp.int32)) % 2 == 0
    valid = jnp.arange(S, dtype=jnp.int32) < S - 3
    gl = gl & valid
    gr = valid & ~gl
    cumL = jnp.cumsum(gl.astype(jnp.int32)); nl = cumL[-1]
    cumR = jnp.cumsum(gr.astype(jnp.int32))
    j = jnp.arange(S, dtype=jnp.int32)
    li = jnp.searchsorted(cumL, j + 1, side='left')
    ri = jnp.searchsorted(cumR, j - nl + 1, side='left')
    idx = jnp.where(j < nl, li, jnp.where(j < S-3, ri, j))
    out = seg[jnp.clip(idx, 0, S-1)]
    return c + out[0].astype(jnp.float32)*1e-9
f = jax.jit(lambda c: jax.lax.scan(lambda c,_: (part_gather(c), None), c, None, length=40)[0])
r = f(jnp.asarray(0.0)); jax.device_get(r)
t0=time.time()
for _ in range(3): r = f(jnp.asarray(0.0)); jax.device_get(r)
print(f"searchsorted-partition S=8192 x40: {(time.time()-t0)/3*1000:.0f} ms total")
