"""Repo-root shim: the canonical implementation lives in the package
(``lightgbm_tpu/utils/hermetic.py``) so library code — e.g. the
multi-process launcher — can use it when installed.  Loaded here by FILE
PATH, not package import: bench.py's outer watchdog process must be able
to build child environments without importing lightgbm_tpu (whose
package __init__ pulls in jax)."""

import importlib.util as _ilu
import os as _os

_spec = _ilu.spec_from_file_location(
    "lightgbm_tpu_hermetic_impl",
    _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                  "lightgbm_tpu", "utils", "hermetic.py"))
_mod = _ilu.module_from_spec(_spec)
_spec.loader.exec_module(_mod)

cpu_env = _mod.cpu_env
force_cpu = _mod.force_cpu
