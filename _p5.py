import time, numpy as np, jax, jax.numpy as jnp
rng = np.random.RandomState(0)
S = 8192
seg0 = jnp.asarray(rng.randint(0,100000,S).astype(np.int32))
def run(variant):
    def body(c):
        seg = seg0 + c.astype(jnp.int32)
        gl = seg % 2 == 0
        lpos = jnp.cumsum(gl.astype(jnp.int32)) - gl
        pos = jnp.where(gl, lpos, jnp.arange(S, dtype=jnp.int32))
        # not a true permutation here but indices stay in range; fine for timing
        if variant == "plain":
            out = jnp.zeros(S, jnp.int32).at[pos].set(seg)
        else:
            out = jnp.zeros(S, jnp.int32).at[pos].set(seg, unique_indices=True, mode='promise_in_bounds')
        return c + out[0].astype(jnp.float32)*1e-9
    f = jax.jit(lambda c: jax.lax.scan(lambda c,_: (body(c), None), c, None, length=40)[0])
    r = f(jnp.asarray(0.0)); jax.device_get(r)
    t0=time.time()
    for _ in range(3): r = f(jnp.asarray(0.0)); jax.device_get(r)
    print(f"{variant}: {(time.time()-t0)/3*1000:.0f} ms total /40")
run("plain"); run("unique")
