"""Regenerate the tiny example datasets (committed alongside the configs,
mirroring the reference's ``examples/*`` layout where each task ships
``<name>.train`` / ``<name>.test`` TSV files with the label in column 0
and ``.query`` side files for ranking).

    python examples/generate_data.py
"""

import os

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def _write(path, y, X):
    np.savetxt(path, np.column_stack([y, X]), delimiter="\t", fmt="%.5g")


def binary(n_train=500, n_test=100, f=10, seed=0):
    rng = np.random.RandomState(seed)
    n = n_train + n_test
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.8 * X[:, 1] * X[:, 2] + 0.3 * rng.randn(n) > 0)
    d = os.path.join(HERE, "binary_classification")
    os.makedirs(d, exist_ok=True)
    _write(os.path.join(d, "binary.train"), y[:n_train], X[:n_train])
    _write(os.path.join(d, "binary.test"), y[n_train:], X[n_train:])


def lambdarank(n_queries=40, docs=20, f=6, seed=1):
    rng = np.random.RandomState(seed)
    n = n_queries * docs
    X = rng.randn(n, f)
    util = X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.randn(n)
    cuts = np.quantile(util, [0.6, 0.9])
    y = np.searchsorted(cuts, util)          # graded relevance 0-2
    d = os.path.join(HERE, "lambdarank")
    os.makedirs(d, exist_ok=True)
    n_train = (n_queries - 8) * docs
    _write(os.path.join(d, "rank.train"), y[:n_train], X[:n_train])
    _write(os.path.join(d, "rank.test"), y[n_train:], X[n_train:])
    np.savetxt(os.path.join(d, "rank.train.query"),
               np.full(n_queries - 8, docs, np.int64), fmt="%d")
    np.savetxt(os.path.join(d, "rank.test.query"),
               np.full(8, docs, np.int64), fmt="%d")


if __name__ == "__main__":
    binary()
    lambdarank()
    print("wrote examples/binary_classification + examples/lambdarank data")
