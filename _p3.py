import time, numpy as np, jax, jax.numpy as jnp
from lightgbm_tpu.ops.histogram import histogram_from_vals

n, F, B, S = 1000000, 28, 255, 8192
rng = np.random.RandomState(0)
bins_pad = jnp.asarray(rng.randint(0,255,(n+1,F)), jnp.uint8)
vals_pad = jnp.asarray(rng.rand(n+1,3).astype(np.float32))
perm = jnp.asarray(rng.permutation(n+1).astype(np.int32))
nanb = jnp.full(F, 255, jnp.int32)

def timeit(name, fn, niter=40, reps=3):
    f = jax.jit(lambda c: jax.lax.scan(lambda c,_: (fn(c), None), c, None, length=niter)[0])
    r = f(jnp.asarray(0.0)); jax.device_get(r)
    t0=time.time()
    for _ in range(reps): r = f(jnp.asarray(0.0)); jax.device_get(r)
    dt=(time.time()-t0)/reps
    print(f"{name}: {(dt/niter)*1000:.3f} ms/iter (total {dt*1000:.0f}ms)")

start = jnp.asarray(1234, jnp.int32)
def seg_of(c):
    return jax.lax.dynamic_slice(perm, (start + (c*0).astype(jnp.int32),), (S,))

timeit("dyn_slice only", lambda c: c + seg_of(c)[0].astype(jnp.float32)*1e-9)
def gather_bins(c):
    seg = seg_of(c)
    bseg = bins_pad[seg]
    return c + bseg[0,0].astype(jnp.float32)*1e-9
timeit("+ bins row-gather SxF", gather_bins)
def gather_vals(c):
    seg = seg_of(c)
    vseg = vals_pad[seg]
    return c + vseg[0,0]*1e-9
timeit("+ vals row-gather Sx3", gather_vals)
def cumsum_scatter(c):
    seg = seg_of(c)
    gl = (seg % 2) == 0
    lpos = jnp.cumsum(gl.astype(jnp.int32)) - gl
    pos = jnp.where(gl, lpos, jnp.arange(S, dtype=jnp.int32))
    new_seg = jnp.zeros(S, jnp.int32).at[pos].set(seg)
    return c + new_seg[0].astype(jnp.float32)*1e-9
timeit("slice+cumsum+scatter", cumsum_scatter)
def hist_only(c):
    seg = seg_of(c)
    bseg = bins_pad[seg]; vseg = vals_pad[seg]
    h = histogram_from_vals(bseg, vseg, num_bins=B, impl="pallas", rows_block=2048)
    return c + h[0,0,0]*1e-9
timeit("slice+gathers+pallas hist", hist_only)
def hist_onehot(c):
    seg = seg_of(c)
    bseg = bins_pad[seg]; vseg = vals_pad[seg]
    h = histogram_from_vals(bseg, vseg, num_bins=B, impl="onehot", rows_block=8192)
    return c + h[0,0,0]*1e-9
timeit("slice+gathers+onehot hist", hist_onehot)
